"""paddle.infer / Inference / Topology — the v2 inference entry point.

Reference: python/paddle/v2/inference.py:24-125 (Inference.iter_infer /
infer), topology.py (Topology.data_type + serialize_for_inference). Every
reference v2 example ends with ``paddle.infer(output_layer=prediction,
parameters=parameters, input=data)`` — this is the recognize_digits-shaped
version of that loop: train with the v2 DSL + SGD, then infer and compare
against the fluid executor's own forward, then round-trip the topology +
parameters through streams into a fresh Inference.
"""

import io

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
import paddle_tpu.v2 as paddle


def _build_and_train():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        pixel = paddle.layer.data("pixel_v2i",
                                  paddle.data_type.dense_vector(16))
        label = paddle.layer.data("label_v2i",
                                  paddle.data_type.integer_value(3))
        hidden = paddle.layer.fc(pixel, size=12,
                                 act=paddle.activation.Relu())
        pred = paddle.layer.fc(hidden, size=3,
                               act=paddle.activation.Softmax())
        cost = paddle.layer.classification_cost(input=pred, label=label)
        params = paddle.parameters.create(cost)
        trainer = paddle.SGD(cost=cost, parameters=params,
                             update_equation=paddle.optimizer.Momentum(
                                 momentum=0.9, learning_rate=0.05),
                             feed_order=["pixel_v2i", "label_v2i"],
                             main_program=main, startup_program=startup)

    w = np.random.RandomState(42).normal(0, 1, (16, 3))
    rng = np.random.RandomState(0)
    xs = rng.normal(0, 1, (192, 16)).astype("float32")
    ys = (xs @ w).argmax(axis=1).astype("int64").reshape(-1, 1)
    data = [(xs[i], ys[i]) for i in range(len(xs))]

    import paddle_tpu.reader as reader_pkg
    trainer.train(reader=reader_pkg.batch(lambda: iter(data), batch_size=32),
                  num_passes=3)
    return trainer, params, pred, xs


def test_infer_matches_fluid_forward():
    trainer, params, pred, xs = _build_and_train()
    samples = [(x,) for x in xs[:10]]

    probs = paddle.infer(output_layer=pred, parameters=params, input=samples)
    assert probs.shape == (10, 3)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-4)

    # must equal the fluid executor's own forward on the test program
    # (the full test clone also carries the cost ops, so label feeds too)
    exe = fluid.Executor()
    direct = exe.run(trainer._test_program,
                     feed={"pixel_v2i": xs[:10],
                           "label_v2i": np.zeros((10, 1), "int64")},
                     fetch_list=[pred.var], scope=trainer.scope)[0]
    np.testing.assert_allclose(probs, np.asarray(direct), rtol=1e-5,
                               atol=1e-6)

    # field='id' returns the argmax labels
    ids = paddle.infer(output_layer=pred, parameters=params, input=samples,
                       field="id")
    np.testing.assert_array_equal(ids, np.argmax(probs, axis=1))

    # feeding dict maps layer names to sample positions
    probs2 = paddle.infer(output_layer=pred, parameters=params,
                          input=[(0, x) for x in xs[:10]],
                          feeding={"pixel_v2i": 1})
    np.testing.assert_allclose(probs2, probs, rtol=1e-6)


def test_infer_batch_size_chunks_match_whole_batch():
    """batch_size= chunks the input through iter_infer instead of the
    reference's single whole-input batch; infer() concatenates the chunks
    back, so results are identical either way."""
    trainer, params, pred, xs = _build_and_train()
    samples = [(x,) for x in xs[:10]]
    whole = paddle.infer(output_layer=pred, parameters=params,
                         input=samples)

    inferer = paddle.Inference(params, output_layer=pred)
    # 10 samples at batch_size=4 -> 3 chunks (4, 4, 2), yielded per chunk
    chunks = list(inferer.iter_infer(samples, batch_size=4))
    assert len(chunks) == 3
    assert np.asarray(chunks[0][0]).shape[0] == 4
    assert np.asarray(chunks[-1][0]).shape[0] == 2
    np.testing.assert_allclose(inferer.infer(input=samples, batch_size=4),
                               whole, rtol=1e-5, atol=1e-6)
    # default None keeps reference behavior: one batch
    assert len(list(inferer.iter_infer(samples))) == 1
    # the top-level spelling routes batch_size too, field='id' included
    ids = paddle.infer(output_layer=pred, parameters=params, input=samples,
                       field="id", batch_size=3)
    np.testing.assert_array_equal(ids, np.argmax(whole, axis=1))
    with pytest.raises(ValueError, match="batch_size"):
        inferer.infer(input=samples, batch_size=0)


def test_topology_serialize_roundtrip():
    trainer, params, pred, xs = _build_and_train()
    samples = [(x,) for x in xs[:6]]
    want = paddle.infer(output_layer=pred, parameters=params, input=samples)

    topo = paddle.Topology(pred)
    assert topo.feed_names == ["pixel_v2i"]
    types = dict(topo.data_type())
    assert types["pixel_v2i"].dim == 16
    assert "pixel_v2i" in topo.proto()

    topo_buf = io.BytesIO()
    topo.serialize_for_inference(topo_buf)
    par_buf = io.BytesIO()
    params.to_tar(par_buf)

    # fresh-process shape: rebuild both from the streams alone
    params2 = paddle.parameters.Parameters.from_tar_file(
        io.BytesIO(par_buf.getvalue()))
    inferer = paddle.Inference(params2,
                               fileobj=io.BytesIO(topo_buf.getvalue()))
    got = inferer.infer(input=samples)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
