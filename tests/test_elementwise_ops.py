"""Elementwise op tests — mirrors reference tests/unittests/
test_elementwise_*_op.py numpy references."""

import numpy as np

from op_test import OpTest


class TestElementwiseAdd(OpTest):
    op_type = "elementwise_add"

    def setup(self):
        x = np.random.uniform(0.1, 1, (4, 5)).astype("float32")
        y = np.random.uniform(0.1, 1, (4, 5)).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x + y}
        self.attrs = {}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X", "Y"], "Out")


class TestElementwiseAddBroadcast(OpTest):
    op_type = "elementwise_add"

    def setup(self):
        x = np.random.rand(2, 3, 4).astype("float32")
        y = np.random.rand(3,).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": x + y.reshape(1, 3, 1)}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X", "Y"], "Out")


class TestElementwiseMul(OpTest):
    op_type = "elementwise_mul"

    def setup(self):
        x = np.random.uniform(0.1, 1, (3, 4)).astype("float32")
        y = np.random.uniform(0.1, 1, (3, 4)).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x * y}
        self.attrs = {}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X", "Y"], "Out")


class TestElementwiseDiv(OpTest):
    op_type = "elementwise_div"

    def setup(self):
        x = np.random.uniform(0.5, 1, (3, 4)).astype("float32")
        y = np.random.uniform(0.5, 1, (3, 4)).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x / y}
        self.attrs = {}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X", "Y"], "Out", max_relative_error=0.05)


class TestElementwiseMaxBroadcastRow(OpTest):
    op_type = "elementwise_max"

    def setup(self):
        x = np.random.uniform(0, 1, (4, 5)).astype("float32")
        y = np.random.uniform(0, 1, (5,)).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": -1}
        self.outputs = {"Out": np.maximum(x, y.reshape(1, 5))}

    def test_output(self):
        self.setup()
        self.check_output()


class TestElementwiseSubBroadcastMid(OpTest):
    op_type = "elementwise_sub"

    def setup(self):
        x = np.random.rand(2, 3, 4, 5).astype("float32")
        y = np.random.rand(3, 4).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": x - y.reshape(1, 3, 4, 1)}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X", "Y"], "Out")
