"""Observability floor: flags registry, NaN/Inf check, Print op, debug dump.

Reference: gflags registry (paddle/utils/Flags.h:19-43, pybind.cc:423
init_gflags), --check_nan_inf sweep (framework/executor.cc:27,325-333),
print op (operators/print_op.cc), program debug strings
(python/paddle/fluid/debuger.py).
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid

layers = fluid.layers


@pytest.fixture(autouse=True)
def _reset_flags():
    yield
    fluid.set_flags({"check_nan_inf": False, "benchmark": False})


def test_flags_registry():
    assert fluid.get_flag("check_nan_inf") is False
    fluid.set_flags({"check_nan_inf": True})
    assert fluid.get_flag("check_nan_inf") is True
    with pytest.raises(KeyError, match="unknown flag"):
        fluid.set_flags({"definitely_not_a_flag": 1})
    assert "benchmark" in fluid.flags()
    # argv-style init (the reference core.init_gflags contract)
    rest = fluid.init_flags(["prog", "--check_nan_inf=0", "--other=x"])
    assert rest == ["prog", "--other=x"]
    assert fluid.get_flag("check_nan_inf") is False


def _nan_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        y = layers.log(x)          # log of a negative -> NaN
        out = layers.mean(y)
    return main, startup, out


@pytest.mark.parametrize("mode", ["eager", "jit"])
def test_check_nan_inf_raises(mode):
    main, startup, out = _nan_program()
    exe = fluid.Executor(fluid.CPUPlace(), mode=mode)
    exe.run(startup)
    bad = np.array([[1.0, 2.0, -3.0, 4.0]], "float32")
    fluid.set_flags({"check_nan_inf": True})
    with pytest.raises(FloatingPointError):
        exe.run(main, feed={"x": bad}, fetch_list=[out])
    # clean input passes
    ok = np.array([[1.0, 2.0, 3.0, 4.0]], "float32")
    v = exe.run(main, feed={"x": ok}, fetch_list=[out])[0]
    assert np.isfinite(v)


def test_check_nan_inf_off_by_default():
    main, startup, out = _nan_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    bad = np.array([[-1.0, 2.0, 3.0, 4.0]], "float32")
    v = exe.run(main, feed={"x": bad}, fetch_list=[out])[0]
    assert np.isnan(v)  # silently propagates, like the reference default


def test_print_op_first_n_and_passthrough(capsys):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[3])
        p = layers.Print(x, first_n=2, message="dbg", summarize=3,
                         print_phase="forward")
        out = layers.scale(p, scale=2.0)
    exe = fluid.Executor(fluid.CPUPlace(), mode="eager")
    exe.run(startup)
    feed = {"x": np.array([[1.0, 2.0, 3.0]], "float32")}
    for _ in range(4):
        v = exe.run(main, feed=feed, fetch_list=[out])[0]
    np.testing.assert_allclose(v, [[2.0, 4.0, 6.0]])  # pass-through intact
    cap = capsys.readouterr().out
    assert cap.count("[print op]") == 2      # first_n honored
    assert "dbg" in cap and "shape=(1, 3)" in cap
    assert "data=[1.0, 2.0, 3.0]" in cap


def test_print_backward_phase_prints_gradient(capsys):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2])
        x.stop_gradient = False
        p = layers.Print(x, message="gradcheck", print_phase="backward")
        loss = layers.mean(layers.scale(p, scale=3.0))
        fluid.append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace(), mode="eager")
    exe.run(startup)
    v = exe.run(main, feed={"x": np.ones((1, 2), "float32")},
                fetch_list=["x@GRAD"])[0]
    np.testing.assert_allclose(v, 1.5 * np.ones((1, 2)))
    cap = capsys.readouterr().out
    assert "gradcheck @GRAD" in cap
    assert "data=[1.5, 1.5]" in cap


def test_program_to_debug_string():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=3, act="softmax")
        loss = layers.mean(layers.cross_entropy(h, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss, startup)
    s = main.to_debug_string()
    assert "block 0 {" in s
    assert "op mul(" in s and "op sgd(" in s
    assert "dtype=int64" in s
    assert "[persistable,param]" in s
    # sub-block-free programs print one block; control flow adds more
    assert s.count("block ") == 1

def test_program_to_graphviz():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        h = layers.fc(x, size=2, param_attr=fluid.ParamAttr(name="gv_w"))
    dot = main.to_graphviz()
    assert dot.startswith("digraph G {") and dot.endswith("}")
    assert '"gv_w" [shape=doublecircle];' in dot   # parameter styling
    assert '"x" -> "op_0_mul";' in dot or '"gv_w" -> "op_0_mul";' in dot


def test_conditional_block_is_lazy_at_runtime(capsys):
    """conditional_block lowers to lax.cond: the untaken branch's ops do
    NOT execute at runtime (the reference's conditional cost model) —
    observable because the Print op's debug callback only fires when its
    branch is taken."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2])
        flag = layers.data("flag", shape=[1], dtype="bool")
        out = layers.fill_constant(shape=[1, 2], dtype="float32", value=0.0)
        sw = fluid.layers.Switch()
        with sw.case(flag):
            p = layers.Print(x, message="taken-branch",
                             print_phase="forward")
            layers.assign(layers.scale(p, scale=2.0), output=out)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed_f = {"x": np.ones((1, 2), "float32"),
              "flag": np.array([[False]])}
    v = exe.run(main, feed=feed_f, fetch_list=[out])[0]
    np.testing.assert_allclose(v, 0.0)
    assert "taken-branch" not in capsys.readouterr().out  # branch skipped

    feed_t = {"x": np.ones((1, 2), "float32"), "flag": np.array([[True]])}
    v = exe.run(main, feed=feed_t, fetch_list=[out])[0]
    np.testing.assert_allclose(v, 2.0)
    assert "taken-branch" in capsys.readouterr().out      # branch executed
