"""Op-breadth numeric tests vs numpy references.

Reference OpTests: test_cumsum_op.py (cum_op.h), test_prelu_op.py,
test_maxout_op.py, test_spp_op.py, test_unpool_op.py, test_norm_op.py,
test_im2sequence_op.py, test_rank_loss_op.py, test_margin_rank_loss_op.py,
test_bilinear_tensor_product_op.py, test_is_empty_op.py, test_nce.py,
test_conv3d_op.py, test_pool3d_op.py (python/paddle/fluid/tests/unittests/).
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid

layers = fluid.layers


def _run(builder, feed, mode="jit"):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        fetch = builder()
    exe = fluid.Executor(fluid.CPUPlace(), mode=mode)
    exe.run(startup)
    return exe.run(main, feed=feed, fetch_list=list(fetch))


@pytest.mark.parametrize("exclusive,reverse", [(False, False), (True, False),
                                               (False, True), (True, True)])
def test_cumsum(exclusive, reverse):
    rng = np.random.RandomState(0)
    x = rng.rand(3, 5).astype("float32")

    def build():
        xv = layers.data("x", shape=[5])
        return [layers.cumsum(xv, axis=1, exclusive=exclusive,
                              reverse=reverse)]

    got, = _run(build, {"x": x})
    v = x[:, ::-1] if reverse else x
    exp = np.cumsum(v, axis=1)
    if exclusive:
        exp = exp - v
    if reverse:
        exp = exp[:, ::-1]
    np.testing.assert_allclose(got, exp, rtol=1e-6)


def test_prelu_trains_alpha():
    rng = np.random.RandomState(1)
    x = rng.normal(0, 1, (8, 4)).astype("float32")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = layers.data("x", shape=[4])
        out = layers.prelu(xv, param_attr=fluid.ParamAttr(name="alpha"))
        loss = layers.mean(out)
        fluid.append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    got, galpha = exe.run(main, feed={"x": x},
                          fetch_list=[out, "alpha@GRAD"])
    np.testing.assert_allclose(got, np.where(x > 0, x, 0.25 * x), rtol=1e-6)
    exp_g = np.where(x > 0, 0, x).sum() / x.size
    np.testing.assert_allclose(np.asarray(galpha).ravel()[0], exp_g,
                               rtol=1e-5)


def test_maxout():
    rng = np.random.RandomState(2)
    x = rng.rand(2, 6, 3, 3).astype("float32")

    def build():
        xv = layers.data("x", shape=[6, 3, 3])
        return [layers.maxout(xv, groups=3)]

    got, = _run(build, {"x": x})
    exp = x.reshape(2, 2, 3, 3, 3).max(axis=2)
    np.testing.assert_allclose(got, exp)


def test_spp_non_divisible_feature_map():
    """7x7 input, pyramid_height=3: output must be exactly C*(1+4+16)
    (reference kernel=ceil/stride=kernel/pad geometry)."""
    rng = np.random.RandomState(30)
    x = rng.rand(2, 2, 7, 7).astype("float32")

    def build():
        xv = layers.data("x", shape=[2, 7, 7])
        return [layers.spp(xv, pyramid_height=3, pool_type="max")]

    got, = _run(build, {"x": x})
    assert got.shape == (2, 2 * (1 + 4 + 16))
    np.testing.assert_allclose(got[:, :2], x.max(axis=(2, 3)), rtol=1e-6)


def test_spp_output():
    rng = np.random.RandomState(3)
    x = rng.rand(2, 3, 8, 8).astype("float32")

    def build():
        xv = layers.data("x", shape=[3, 8, 8])
        return [layers.spp(xv, pyramid_height=2, pool_type="max")]

    got, = _run(build, {"x": x})
    assert got.shape == (2, 3 * (1 + 4))
    np.testing.assert_allclose(got[:, :3], x.max(axis=(2, 3)), rtol=1e-6)
    # level 1, bin (0,0) = max of the top-left 4x4 quadrant
    np.testing.assert_allclose(got[:, 3], x[:, 0, :4, :4].max(axis=(1, 2)),
                               rtol=1e-6)


def test_max_pool_with_index_and_unpool_roundtrip():
    rng = np.random.RandomState(4)
    x = rng.rand(2, 2, 4, 4).astype("float32")

    def build():
        xv = layers.data("x", shape=[2, 4, 4])
        pooled, mask = layers.max_pool2d_with_index(xv, pool_size=2,
                                                    pool_stride=2)
        up = layers.unpool(pooled, mask, unpooled_size=[4, 4])
        return [pooled, mask, up]

    pooled, mask, up = _run(build, {"x": x})
    exp_pool = x.reshape(2, 2, 2, 2, 2, 2).max(axis=(3, 5))
    np.testing.assert_allclose(pooled, exp_pool)
    # unpool scatters each max back to its original position
    for n in range(2):
        for c in range(2):
            nz = up[n, c][up[n, c] != 0]
            np.testing.assert_allclose(np.sort(nz),
                                       np.sort(pooled[n, c].ravel()))


def test_norm_cross_channel():
    rng = np.random.RandomState(5)
    x = rng.rand(2, 4, 3, 3).astype("float32") + 0.1

    def build():
        xv = layers.data("x", shape=[4, 3, 3])
        return [layers.norm(xv, param_attr=fluid.ParamAttr(name="nsc"))]

    got, = _run(build, {"x": x})
    denom = np.sqrt((x ** 2).sum(axis=1, keepdims=True) + 1e-10)
    np.testing.assert_allclose(got, x / denom, rtol=1e-5)


def test_im2sequence():
    rng = np.random.RandomState(6)
    x = rng.rand(2, 2, 4, 4).astype("float32")

    def build():
        xv = layers.data("x", shape=[2, 4, 4])
        return [layers.im2sequence(xv, filter_size=2, stride=2)]

    out, = _run(build, {"x": x})
    data = np.asarray(out.data)
    lens = np.asarray(out.lens)
    assert data.shape == (2, 4, 2 * 2 * 2) and (lens == 4).all()
    # step 0 = top-left 2x2 patch of each channel, [c, kh, kw] flattened
    exp0 = x[:, :, :2, :2].reshape(2, -1)
    np.testing.assert_allclose(data[:, 0], exp0)


def test_rank_loss_and_grad():
    rng = np.random.RandomState(7)
    label = (rng.rand(6, 1) > 0.5).astype("float32")
    left = rng.normal(0, 1, (6, 1)).astype("float32")
    right = rng.normal(0, 1, (6, 1)).astype("float32")

    def build():
        l = layers.data("label", shape=[1])
        a = layers.data("left", shape=[1])
        b = layers.data("right", shape=[1])
        out = layers.rank_loss(l, a, b)
        loss = layers.mean(out)
        fluid.append_backward(loss)
        return [out, "left@GRAD"]

    out, gleft = _run(build, {"label": label, "left": left, "right": right})
    exp = np.log1p(np.exp(left - right)) - label * (left - right)
    np.testing.assert_allclose(out, exp, rtol=1e-5)
    sig = 1 / (1 + np.exp(right - left))
    np.testing.assert_allclose(gleft, (sig - label) / 6.0, rtol=1e-5)


def test_margin_rank_loss():
    label = np.array([[1.0], [-1.0], [1.0]], "float32")
    x1 = np.array([[0.5], [0.5], [0.1]], "float32")
    x2 = np.array([[0.3], [0.3], [0.4]], "float32")

    def build():
        l = layers.data("label", shape=[1])
        a = layers.data("x1", shape=[1])
        b = layers.data("x2", shape=[1])
        return [layers.margin_rank_loss(l, a, b, margin=0.1)]

    out, = _run(build, {"label": label, "x1": x1, "x2": x2})
    exp = np.maximum(0.0, -label * (x1 - x2) + 0.1)
    np.testing.assert_allclose(out, exp, rtol=1e-6)


def test_bilinear_tensor_product():
    rng = np.random.RandomState(8)
    x = rng.rand(3, 4).astype("float32")
    y = rng.rand(3, 5).astype("float32")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = layers.data("x", shape=[4])
        yv = layers.data("y", shape=[5])
        out = layers.bilinear_tensor_product(
            xv, yv, size=2, param_attr=fluid.ParamAttr(name="btp_w"),
            bias_attr=fluid.ParamAttr(name="btp_b"))
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    got = exe.run(main, feed={"x": x, "y": y}, fetch_list=[out],
                  scope=scope)[0]
    w = np.asarray(scope.find_var("btp_w"))
    b = np.asarray(scope.find_var("btp_b"))
    exp = np.stack([np.sum(x @ w[k] * y, axis=1) for k in range(2)],
                   axis=1) + b
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)


def test_is_empty():
    def build():
        xv = layers.data("x", shape=[3])
        return [layers.is_empty(xv)]

    got, = _run(build, {"x": np.zeros((2, 3), "float32")}, mode="eager")
    assert bool(np.asarray(got)[0]) is False
    got2, = _run(build, {"x": np.zeros((0, 3), "float32")}, mode="eager")
    assert bool(np.asarray(got2)[0]) is True


def test_nce_matches_numpy_with_custom_negatives():
    """custom_neg_classes pins the sample set (the reference's own unit-test
    hook), making the cost deterministic and numpy-checkable."""
    rng = np.random.RandomState(9)
    b, d, C = 4, 6, 8
    x = rng.normal(0, 1, (b, d)).astype("float32")
    label = rng.randint(0, C, (b, 1)).astype("int64")
    negs = [5, 6]

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = layers.data("x", shape=[d])
        lv = layers.data("label", shape=[1], dtype="int64")
        cost = layers.nce(xv, lv, num_total_classes=C,
                          num_neg_samples=len(negs),
                          custom_neg_classes=negs,
                          param_attr=fluid.ParamAttr(name="nce_w"),
                          bias_attr=fluid.ParamAttr(name="nce_b"))
        loss = layers.mean(cost)
        fluid.optimizer.SGD(learning_rate=0.5).minimize(loss, startup)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    w = np.asarray(scope.find_var("nce_w")).copy()
    bb = np.asarray(scope.find_var("nce_b")).copy()
    got, = exe.run(main, feed={"x": x, "label": label}, fetch_list=[cost],
                   scope=scope)

    bconst = len(negs) / C
    exp = np.zeros((b, 1), "float32")
    for i in range(b):
        samples = [int(label[i, 0])] + negs
        for j, c in enumerate(samples):
            o = 1 / (1 + np.exp(-(x[i] @ w[c] + bb[c])))
            exp[i, 0] += -np.log(o / (o + bconst)) if j == 0 \
                else -np.log(bconst / (o + bconst))
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)

    # and it trains: repeated steps reduce the loss
    losses = [float(exe.run(main, feed={"x": x, "label": label},
                            fetch_list=[loss], scope=scope)[0])
              for _ in range(20)]
    assert losses[-1] < 0.6 * losses[0]


def test_conv3d_matches_numpy():
    rng = np.random.RandomState(10)
    x = rng.rand(1, 2, 4, 4, 4).astype("float32")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = layers.data("x", shape=[2, 4, 4, 4])
        out = layers.conv3d(xv, num_filters=3, filter_size=2,
                            bias_attr=False,
                            param_attr=fluid.ParamAttr(name="c3w"))
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    got = exe.run(main, feed={"x": x}, fetch_list=[out], scope=scope)[0]
    w = np.asarray(scope.find_var("c3w"))
    exp = np.zeros((1, 3, 3, 3, 3), "float32")
    for o in range(3):
        for dz in range(3):
            for dy in range(3):
                for dx in range(3):
                    exp[0, o, dz, dy, dx] = np.sum(
                        x[0, :, dz:dz + 2, dy:dy + 2, dx:dx + 2] * w[o])
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("ptype", ["max", "avg"])
def test_pool3d(ptype):
    rng = np.random.RandomState(11)
    x = rng.rand(1, 2, 4, 4, 4).astype("float32")

    def build():
        xv = layers.data("x", shape=[2, 4, 4, 4])
        return [layers.pool3d(xv, pool_size=2, pool_type=ptype,
                              pool_stride=2)]

    got, = _run(build, {"x": x})
    blocks = x.reshape(1, 2, 2, 2, 2, 2, 2, 2)
    r = blocks.transpose(0, 1, 2, 4, 6, 3, 5, 7).reshape(1, 2, 2, 2, 2, -1)
    exp = r.max(-1) if ptype == "max" else r.mean(-1)
    np.testing.assert_allclose(got, exp, rtol=1e-5)


def test_ifelse_select_semantics_and_grad():
    rng = np.random.RandomState(12)
    x = rng.normal(0, 1, (6, 3)).astype("float32")
    cond_np = (rng.rand(6, 1) > 0.5).astype("bool")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = layers.data("x", shape=[3])
        xv.stop_gradient = False
        cv = layers.data("c", shape=[1], dtype="bool")
        ie = layers.IfElse(cv)
        with ie.true_block():
            ie.output(layers.scale(ie.input(xv), scale=2.0))
        with ie.false_block():
            ie.output(layers.scale(ie.input(xv), scale=-1.0))
        merged, = ie()
        loss = layers.mean(merged)
        fluid.append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    got, gx = exe.run(main, feed={"x": x, "c": cond_np},
                      fetch_list=[merged, "x@GRAD"])
    exp = np.where(cond_np, 2.0 * x, -1.0 * x)
    np.testing.assert_allclose(got, exp, rtol=1e-6)
    exp_g = np.where(cond_np, 2.0, -1.0) / x.size * np.ones_like(x)
    np.testing.assert_allclose(gx, exp_g, rtol=1e-5)


def test_checkpoint_manifest_and_torn_save_detection(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        layers.fc(x, size=2, param_attr=fluid.ParamAttr(name="ckw"))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    d = str(tmp_path / "ckpt")
    fluid.io.save_params(exe, d, main)
    import json
    import os
    manifest = json.load(open(os.path.join(d, "MANIFEST.json")))
    assert "ckw" in manifest and manifest["ckw"]["shape"] == [4, 2]
    # torn checkpoint: delete a var file the manifest lists
    os.remove(os.path.join(d, "ckw.npy"))
    from paddle_tpu.core.scope import reset_global_scope
    reset_global_scope()
    with pytest.raises(RuntimeError, match="torn"):
        fluid.io.load_params(exe, d, main)
    # saving vars absent from the scope is an error, not a silent skip
    reset_global_scope()
    with pytest.raises(RuntimeError, match="absent from the scope"):
        fluid.io.save_params(exe, str(tmp_path / "c2"), main)