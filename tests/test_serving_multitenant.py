"""Multi-tenant serving plane: N engines per replica behind one RPC
endpoint (model= routing, bitwise parity with dedicated single-model
servers, refcount-aware LRU eviction, per-model reload isolation),
per-tenant token-bucket quotas with the typed QuotaExceeded wire
contract (quota rejects never fail over), the first-class queue-depth
gauge, ChildSupervisor dynamic membership (add/retire under the
monitor), and the FleetAutoscaler control loop against a scripted fleet.
"""

import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.distributed import RemoteError, RetryPolicy
from paddle_tpu.distributed.launch import ChildSupervisor
from paddle_tpu.obs.metrics import REGISTRY
from paddle_tpu.serving import (FleetAutoscaler, FleetClient, GenClient,
                                InferClient, InferenceEngine, ModelServer,
                                QuotaExceeded, ServerOverloaded,
                                TenantQuotas)
from paddle_tpu.testing.models import export_tiny_lm

VOCAB = 13
GEN_OPTS = dict(max_seqs=4, block_size=4, num_blocks=64, max_len=32,
                prefill_buckets=(8,))


def _export_model(tmp_path, name="model", weight_shift=0.0, dim=6,
                  hidden=8, classes=3, n=16):
    """Export a tiny MLP; ``weight_shift`` perturbs the params so two
    exports are DIFFERENT models. Returns (dir, inputs, reference)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[dim])
        h = fluid.layers.fc(input=x, size=hidden, act="relu")
        y = fluid.layers.fc(input=h, size=classes, act="softmax")
    exe = fluid.Executor()
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    if weight_shift:
        for p in main.all_parameters():
            v = np.asarray(scope.find_var(p.name))
            scope.set(p.name, v + np.float32(weight_shift))
    d = str(tmp_path / name)
    fluid.io.save_inference_model(d, ["x"], [y], exe, main, scope=scope)
    rng = np.random.RandomState(0)
    xs = rng.normal(0, 1, (n, dim)).astype("float32")
    want = exe.run(main, feed={"x": xs}, fetch_list=[y], scope=scope)[0]
    return d, xs, want


# ---------------------------------------------------------------------------
# Multi-model hosting: routing, parity, eviction, reload isolation
# ---------------------------------------------------------------------------

def test_two_models_one_server_bitwise_matches_two_solo_servers(tmp_path):
    """A hosts-B server answers BOTH models bitwise-identically to two
    dedicated single-model servers — co-hosting shares the endpoint, not
    the numerics."""
    dA, xs, _ = _export_model(tmp_path, "a")
    dB, _, _ = _export_model(tmp_path, "b", weight_shift=0.25)
    soloA = ModelServer(dA, buckets="1,2,4,8", max_delay_ms=1.0)
    soloA.start()
    soloB = ModelServer(dB, buckets="1,2,4,8", max_delay_ms=1.0)
    soloB.start()
    multi = ModelServer(dA, buckets="1,2,4,8", max_delay_ms=1.0)
    multi.start()
    added = multi.add_model("bee", model_dir=dB, buckets="1,2,4,8")
    assert added["model"] == "bee" and added["evicted"] == []
    try:
        with InferClient(soloA.address) as ca, \
                InferClient(soloB.address) as cb, \
                InferClient(multi.address) as cm:
            for n in (1, 3, 8):
                wantA = ca.infer({"x": xs[:n]})[0]
                wantB = cb.infer({"x": xs[:n]})[0]
                gotA = cm.infer({"x": xs[:n]})[0]          # default model
                gotB = cm.infer({"x": xs[:n]}, model="bee")[0]
                assert np.array_equal(gotA, wantA)
                assert np.array_equal(gotB, wantB)
            h = cm.health()
            assert h["status"] == "serving"
            assert h["models"]["bee"]["model_kind"] == "feedforward"
            assert h["models"]["bee"]["inflight"] == 0
            st = cm.stats()
            assert st["models"]["bee"]["engine"]["hot_recompiles"] == 0
            # solo clients never see a "models" section (bitwise-compat
            # health/stats shapes for single-model servers)
            assert "models" not in ca.health()
            with pytest.raises(RemoteError, match="unknown model"):
                cm.infer({"x": xs[:1]}, model="nope")
    finally:
        assert multi.shutdown()
        soloA.shutdown()
        soloB.shutdown()


def test_generative_model_beside_feedforward_default(tmp_path):
    """Feed-forward default + named generative LM on ONE server: greedy
    generate via model= matches a dedicated generative server token for
    token, and the wrong-surface calls stay typed."""
    dF, xs, _ = _export_model(tmp_path, "ff")
    dLM = str(tmp_path / "lm")
    export_tiny_lm(dLM, vocab=VOCAB, emb=8, heads=2, n_layers=2,
                   max_pos=64, seed=3)
    solo = ModelServer(dLM, model_kind="generative", gen_opts=GEN_OPTS)
    solo.start()
    multi = ModelServer(dF, buckets="1,2,4", max_delay_ms=1.0)
    multi.start()
    multi.add_model("lm", model_dir=dLM, model_kind="generative",
                    gen_opts=GEN_OPTS)
    try:
        with GenClient(solo.address) as cs:
            want = list(cs.generate([1, 2, 3], 6))
        with GenClient(multi.address) as cg:
            got = list(cg.generate([1, 2, 3], 6, model="lm"))
        assert got == want and len(got) == 6
        with InferClient(multi.address) as ci:
            out = ci.infer({"x": xs[:2]})            # default ff intact
            assert out[0].shape == (2, 3)
            with pytest.raises(RemoteError, match="GENERATIVE"):
                ci.infer({"x": xs[:1]}, model="lm")
        h = multi.health()
        assert h["models"]["lm"]["model_kind"] == "generative"
        assert h["models"]["lm"]["warmed"]
    finally:
        assert multi.shutdown()
        solo.shutdown()


def test_lru_evicts_idle_never_inflight(tmp_path):
    """The model budget evicts the LEAST-RECENTLY-USED idle model; a
    model with in-flight requests is never a candidate, and a budget
    full of pinned models refuses the add instead of evicting one."""
    dirs = {}
    for name, shift in (("a", 0.0), ("b", 0.1), ("c", 0.2), ("d", 0.3)):
        dirs[name], xs, _ = _export_model(tmp_path, name,
                                          weight_shift=shift)
    srv = ModelServer(dirs["a"], buckets="1,2", max_delay_ms=1.0,
                      max_models=3)            # default + 2 named slots
    srv.start()
    try:
        srv.add_model("b", model_dir=dirs["b"], buckets="1,2")
        srv.add_model("c", model_dir=dirs["c"], buckets="1,2")
        with InferClient(srv.address) as c:
            c.infer({"x": xs[:1]}, model="b")    # b now fresher than c
        out = srv.add_model("d", model_dir=dirs["d"], buckets="1,2")
        assert out["evicted"] == ["c"]           # LRU, not insertion order
        assert sorted(srv.health()["models"]) == ["b", "d"]
        # pin BOTH hosted models in flight: the evictor must refuse
        hb = srv._checkout("b")
        hd = srv._checkout("d")
        try:
            with pytest.raises(RuntimeError, match="in-flight"):
                srv.add_model("c", model_dir=dirs["c"], buckets="1,2")
        finally:
            srv._checkin(hb)
            srv._checkin(hd)
        # idle again: the same add now succeeds by evicting the LRU
        out = srv.add_model("c", model_dir=dirs["c"], buckets="1,2")
        assert len(out["evicted"]) == 1
        # remove_model refuses while in flight, typed
        hc = srv._checkout("c")
        with pytest.raises(RuntimeError, match="in-flight"):
            srv.remove_model("c")
        srv._checkin(hc)
        assert srv.remove_model("c")["removed"]
    finally:
        assert srv.shutdown()


def test_reload_one_model_leaves_the_other_untouched(tmp_path):
    """reload(model=...) swaps ONE hosted model's engine; the default
    model keeps its engine OBJECT and its compile log stays flat."""
    dA, xs, _ = _export_model(tmp_path, "a")
    dB, _, _ = _export_model(tmp_path, "b", weight_shift=0.1)
    dB2, _, wantB2 = _export_model(tmp_path, "b2", weight_shift=0.7)
    srv = ModelServer(dA, buckets="1,2,4", max_delay_ms=1.0)
    srv.start()
    srv.add_model("bee", model_dir=dB, buckets="1,2,4")
    try:
        with InferClient(srv.address) as c:
            before = c.infer({"x": xs[:2]})[0]
            engineA = srv.engine
            compilesA = srv.engine.stats()["compiles"]
            out = srv.reload(dB2, model="bee", version=2)
            assert out["model"] == "bee" and out["version"] == 2
            got = c.infer({"x": xs[:4]}, model="bee")[0]
            np.testing.assert_allclose(got, wantB2[:4], rtol=1e-5,
                                       atol=1e-6)
            # the DEFAULT model: same engine object, zero new compiles,
            # identical answers
            assert srv.engine is engineA
            assert srv.engine.stats()["compiles"] == compilesA
            assert srv.engine.stats()["hot_recompiles"] == 0
            assert np.array_equal(c.infer({"x": xs[:2]})[0], before)
            assert c.health()["models"]["bee"]["version"] == 2
            assert srv.stats()["models"]["bee"]["reloads"] == 1
    finally:
        assert srv.shutdown()


# ---------------------------------------------------------------------------
# Tenant quotas: token bucket, wire contract, router non-failover
# ---------------------------------------------------------------------------

def test_tenant_quotas_token_bucket_and_label_funnel():
    q = TenantQuotas(rate=0.01, burst=2, overrides={"gold": (0.01, 5)},
                     label_cap=3)
    for _ in range(2):
        assert q.try_acquire("t0") == (True, 0.0)
    admitted, retry = q.try_acquire("t0")
    assert not admitted and retry > 0
    with pytest.raises(QuotaExceeded) as ei:
        q.check("t0")
    assert ei.value.tenant == "t0" and ei.value.retry_after_s > 0
    # per-tenant override: gold's burst of 5 admits where t0 rejected
    for _ in range(5):
        assert q.try_acquire("gold")[0]
    assert not q.try_acquire("gold")[0]
    st = q.stats()
    assert st["tenants"]["t0"] == {"admitted": 2, "rejected": 2}
    assert st["tenants"]["gold"]["admitted"] == 5
    # metric-label funnel: enforcement stays EXACT per tenant, but past
    # the label cap (and for non-identifier names) the metric children
    # collapse into __other__ — bounded cardinality under tenant floods
    for t in ("t1", "t2", "t3", "t4", "bad name!"):
        q.try_acquire(t)
    fam = REGISTRY.snapshot()["paddle_tpu_tenant_requests"]
    mine = {v["labels"]["tenant"] for v in fam["values"]
            if v["labels"]["instance"] == q.obs_instance}
    assert "__other__" in mine
    assert "t4" not in mine and "bad name!" not in mine
    assert len(st["overrides"]) == 1


def test_rate_zero_means_unlimited():
    q = TenantQuotas(rate=0.0)
    for _ in range(50):
        assert q.try_acquire("anyone")[0]
    q.check("anyone")                      # never raises


def test_both_wire_codes_roundtrip_typed(tmp_path):
    """ServerOverloaded and QuotaExceeded both cross the wire as
    structured codes and re-raise as their OWN types client-side; other
    remote failures stay RemoteError."""
    d, xs, _ = _export_model(tmp_path)
    eng = InferenceEngine(d, buckets="1,2")
    release = threading.Event()
    inner = eng.infer

    def slow_infer(feed, fetch_list=None):
        release.wait(5.0)
        return inner(feed, fetch_list)

    eng.infer = slow_infer
    srv = ModelServer(engine=eng, batching=True, queue_capacity=1,
                      max_delay_ms=1.0,
                      tenant_quotas=TenantQuotas(rate=0.01, burst=1))
    srv.start()
    outcomes = []

    def caller(i):
        with InferClient(srv.address, retry=None) as c:
            try:
                c.infer({"x": xs[i:i + 1]})
                outcomes.append("ok")
            except ServerOverloaded:
                outcomes.append("overloaded")

    ts = [threading.Thread(target=caller, args=(i,)) for i in range(5)]
    for t in ts:
        t.start()
    deadline = time.monotonic() + 3.0
    while outcomes.count("overloaded") < 1 and time.monotonic() < deadline:
        time.sleep(0.005)
    release.set()
    for t in ts:
        t.join()
    assert outcomes.count("overloaded") >= 1
    try:
        with InferClient(srv.address, retry=None) as c:
            c.infer({"x": xs[:1]}, tenant="burst")       # spends the burst
            with pytest.raises(QuotaExceeded, match="quota"):
                c.infer({"x": xs[:1]}, tenant="burst")
            with pytest.raises(RemoteError, match="unknown model"):
                c.infer({"x": xs[:1]}, model="ghost")
    finally:
        srv.shutdown()


def test_router_quota_rejects_do_not_fail_over(tmp_path):
    """A noisy tenant's quota rejects surface typed at the router and
    bump quota_rejects ONLY — zero failovers, zero spillovers, zero
    requests reaching any replica for the rejected calls."""
    d, xs, _ = _export_model(tmp_path)
    s1 = ModelServer(d, buckets="1,2,4", max_delay_ms=1.0)
    s1.start()
    s2 = ModelServer(d, buckets="1,2,4", max_delay_ms=1.0)
    s2.start()
    fc = FleetClient([s1.address, s2.address],
                     retry=RetryPolicy(max_retries=2, backoff_base_s=0.01),
                     quotas=TenantQuotas(rate=0.01, burst=2))
    try:
        served = 0
        rejected = 0
        for _ in range(6):
            try:
                fc.infer({"x": xs[:1]}, tenant="noisy")
                served += 1
            except QuotaExceeded:
                rejected += 1
        assert served == 2 and rejected == 4
        fc.infer({"x": xs[:1]})                # untenanted: unaffected
        st = fc.fleet_stats(include_server_stats=True)
        assert st["quota_rejects"] == 4
        assert st["failovers"] == 0
        assert st["spillovers"] == 0
        assert st["quotas"]["tenants"]["noisy"]["rejected"] == 4
        # the replicas saw only the ADMITTED requests
        served_fleet = sum(r["server"]["batcher"]["requests"]
                           for r in st["replicas"])
        assert served_fleet == 3
    finally:
        fc.close()
        s1.shutdown()
        s2.shutdown()


def test_router_dynamic_membership(tmp_path):
    """add_replica joins a scaled-out replica to the routing set (and
    really routes to it); remove_replica drops it and refuses to empty
    the set."""
    d, xs, _ = _export_model(tmp_path)
    s1 = ModelServer(d, buckets="1,2", max_delay_ms=1.0)
    s1.start()
    s2 = ModelServer(d, buckets="1,2", max_delay_ms=1.0)
    s2.start()
    fc = FleetClient([s1.address], retry=RetryPolicy(max_retries=2))
    try:
        assert fc.add_replica(s2.address)
        assert not fc.add_replica(s2.address)     # idempotent
        for _ in range(24):
            fc.infer({"x": xs[:1]})
        st = fc.fleet_stats(include_server_stats=True)
        served = [r["server"]["batcher"]["requests"]
                  for r in st["replicas"]]
        assert len(served) == 2 and all(s > 0 for s in served)
        assert fc.remove_replica(s2.address)
        assert not fc.remove_replica(s2.address)  # already gone
        fc.infer({"x": xs[:1]})                   # survivor still serves
        with pytest.raises(ValueError, match="last replica"):
            fc.remove_replica(s1.address)
    finally:
        fc.close()
        s1.shutdown()
        s2.shutdown()


# ---------------------------------------------------------------------------
# Queue-depth gauge: O(1) first-class read
# ---------------------------------------------------------------------------

def test_queue_depth_gauge_tracks_pending(tmp_path):
    from paddle_tpu.serving.batcher import DynamicBatcher

    gate = threading.Event()
    entered = threading.Event()

    def run_batch(feed, fetch_list=None):
        entered.set()
        gate.wait(5.0)
        return [np.asarray(feed["x"])]

    b = DynamicBatcher(run_batch, max_batch=1, max_delay_ms=1.0,
                       capacity=8)

    def depth():
        fam = REGISTRY.snapshot()["paddle_tpu_server_queue_depth"]
        for v in fam["values"]:
            if v["labels"]["instance"] == b.obs_instance:
                return v["value"]
        return None

    assert depth() == 0
    ts = [threading.Thread(target=lambda: b.submit({"x": np.zeros((1, 2))}))
          for _ in range(4)]
    for t in ts:
        t.start()
    assert entered.wait(5.0)
    deadline = time.monotonic() + 3.0
    while (depth() or 0) < 1 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert depth() >= 1                     # queued behind the held batch
    gate.set()
    for t in ts:
        t.join()
    assert b.close(5.0)
    assert depth() == 0


# ---------------------------------------------------------------------------
# ChildSupervisor dynamic membership
# ---------------------------------------------------------------------------

def _echo_child(address, token):
    from paddle_tpu.distributed.rpc import RpcServer

    class H:
        def stats(self):
            return {"token": token, "pid": os.getpid()}

    RpcServer(H(), tuple(address)).serve_forever()


class _EchoSupervisor(ChildSupervisor):
    def _child_spec(self, i):
        return _echo_child, (self.addresses[i], i)


def test_child_supervisor_add_and_retire_members():
    from paddle_tpu.distributed.rpc import RpcClient

    retry = RetryPolicy(max_retries=25, backoff_base_s=0.05,
                        backoff_max_s=0.25)
    with _EchoSupervisor(1, heartbeat_interval_s=0.1) as sup:
        assert sup.wait_ready(20.0)
        assert sup.n_children == 1
        addr1 = sup.add_child()
        assert sup.n_children == 2 and sup.addresses[1] == addr1
        c = RpcClient(addr1, timeout=5.0, retry=retry)
        assert c.call("stats")["token"] == 1     # the NEW child answers
        # the added child is a full member: the monitor restarts it
        pid_before = c.call("stats")["pid"]
        sup.kill(1)
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            try:
                if c.call("stats")["pid"] != pid_before:
                    break
            except Exception:
                pass
            time.sleep(0.05)
        assert c.call("stats")["pid"] != pid_before
        c.close()
        # retire the tail member: the survivor keeps serving on its
        # address and the retired child is NOT respawned
        gone = sup.retire_child()
        assert gone == addr1 and sup.n_children == 1
        c0 = RpcClient(sup.addresses[0], timeout=5.0, retry=retry)
        assert c0.call("stats")["token"] == 0
        c0.close()
        time.sleep(0.4)                          # a few monitor beats
        assert sup.n_children == 1


# ---------------------------------------------------------------------------
# FleetAutoscaler control loop (scripted fleet — no processes)
# ---------------------------------------------------------------------------

class _ScriptedFleet:
    """Duck-typed FleetSupervisor: fleet_metrics() serves the scripted
    queue depth; spawn/retire mutate the address list."""

    def __init__(self, canary_ok=True):
        self.addresses = [("127.0.0.1", 9001)]
        self.depth = 0.0
        self.canary_ok = canary_ok
        self.version = 1
        self.model = "m"
        self.registry = self
        self.warm_calls = 0
        self.spawns = 0
        self.retires = 0

    def warm(self, model, version=None, **kw):
        self.warm_calls += 1

    def fleet_metrics(self, timeout=2.0, include_local=False):
        fam = {"type": "gauge", "help": "", "labels": ["instance"],
               "values": [{"labels": {"instance": "b0"},
                           "value": self.depth}]}
        return {"merged": {"paddle_tpu_server_queue_depth": fam},
                "queue_depth": {"replicas": {0: self.depth},
                                "total": self.depth}}

    def spawn_replica(self, wait_timeout=None):
        self.spawns += 1
        self.addresses.append(("127.0.0.1", 9001 + len(self.addresses)))
        return len(self.addresses) - 1, self.addresses[-1]

    def _await_replica(self, i, deadline, target_version=None):
        if not self.canary_ok:
            raise TimeoutError("canary never went healthy")

    def retire_replica(self, timeout=10.0):
        self.retires += 1
        return self.addresses.pop()


def test_autoscaler_breach_scales_out_idle_scales_in():
    sup = _ScriptedFleet()
    breaches = []
    asc = FleetAutoscaler(sup, min_replicas=1, max_replicas=2,
                          poll_s=0.5, idle_polls=2,
                          on_breach=breaches.append)
    # queue depth over objective -> breach -> ONE warm scale-out
    sup.depth = 100.0
    status = asc.poll_once()
    assert not status["serving_fleet_queue_depth"]["ok"]
    assert sup.spawns == 1 and sup.warm_calls == 1
    assert len(sup.addresses) == 2 and len(breaches) == 1
    # still burning at max_replicas: no further spawns
    asc.poll_once()
    assert sup.spawns == 1
    # recovery: wait out the burn window, then idle_polls empty polls
    sup.depth = 0.0
    time.sleep(1.1)
    st1 = asc.poll_once()
    assert st1["serving_fleet_queue_depth"]["ok"]
    assert sup.retires == 0                  # idle streak not met yet
    asc.poll_once()
    assert sup.retires == 1                  # scaled back in...
    assert len(sup.addresses) == 1
    asc.poll_once()
    asc.poll_once()
    assert sup.retires == 1                  # ...but never below min
    s = asc.stats()
    assert s["scale_ups"] == 1 and s["scale_downs"] == 1
    assert s["replicas"] == 1 and s["canary_failures"] == 0
    assert not s["breach_active"]


def test_autoscaler_failed_canary_is_retired_not_routed():
    sup = _ScriptedFleet(canary_ok=False)
    asc = FleetAutoscaler(sup, min_replicas=1, max_replicas=3,
                          poll_s=0.5, idle_polls=2)
    sup.depth = 100.0
    asc.poll_once()
    # the spawn happened but the canary gate failed: the replica was
    # retired again, the fleet is back to its pre-spawn size
    assert sup.spawns == 1 and sup.retires == 1
    assert len(sup.addresses) == 1
    assert asc.stats()["canary_failures"] == 1
    assert asc.stats()["scale_ups"] == 0


def test_autoscaler_background_loop_and_bounds():
    with pytest.raises(ValueError, match="min_replicas"):
        FleetAutoscaler(_ScriptedFleet(), min_replicas=3, max_replicas=2)
    sup = _ScriptedFleet()
    asc = FleetAutoscaler(sup, min_replicas=1, max_replicas=2,
                          poll_s=0.05, idle_polls=2, registry_warm=False)
    sup.depth = 50.0
    with asc.start():
        deadline = time.monotonic() + 5.0
        while sup.spawns < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
    assert sup.spawns == 1 and sup.warm_calls == 0
    assert asc.stats()["last_error"] is None
