"""Serving fleet control plane: the versioned ModelRegistry (atomic
publish, immutability, corruption detection), ModelServer zero-downtime
hot reload, the FleetClient router (balancing, failover, overload
spillover, probation re-admission) against in-process servers, and the
spawned-replica FleetSupervisor end to end — rolling reload keeping ≥N−1
replicas ready, failed-canary rollback, and crash-failover-rejoin under a
deterministic FaultPlan.
"""

import json
import os
import threading
import time

import jax
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.distributed import FaultPlan, RemoteError, RetryPolicy
from paddle_tpu.distributed.launch import ChildSupervisor, PserverSupervisor
from paddle_tpu.serving import (FleetClient, FleetSupervisor, InferClient,
                                ModelRegistry, ModelServer, ServerOverloaded)


def _export_model(tmp_path, name="model", weight_shift=0.0, dim=6, hidden=8,
                  classes=3, n=16):
    """Export a tiny MLP; ``weight_shift`` perturbs the params post-init so
    two exports produce DIFFERENT models (init is deterministic per var
    name). Returns (model_dir, inputs, reference outputs)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[dim])
        h = fluid.layers.fc(input=x, size=hidden, act="relu")
        y = fluid.layers.fc(input=h, size=classes, act="softmax")
    exe = fluid.Executor()
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    if weight_shift:
        for p in main.all_parameters():
            v = np.asarray(scope.find_var(p.name))
            scope.set(p.name, v + np.float32(weight_shift))
    d = str(tmp_path / name)
    fluid.io.save_inference_model(d, ["x"], [y], exe, main, scope=scope)
    rng = np.random.RandomState(0)
    xs = rng.normal(0, 1, (n, dim)).astype("float32")
    want = exe.run(main, feed={"x": xs}, fetch_list=[y], scope=scope)[0]
    return d, xs, want


# ---------------------------------------------------------------------------
# ModelRegistry: atomic versioned publish, resolve, corruption detection
# ---------------------------------------------------------------------------

def test_registry_publish_resolve_and_latest(tmp_path):
    d, _, _ = _export_model(tmp_path)
    reg = ModelRegistry(str(tmp_path / "registry"))
    assert reg.versions("mlp") == []
    v1 = reg.publish("mlp", d)
    v2 = reg.publish("mlp", d)
    assert (v1, v2) == (1, 2) and reg.versions("mlp") == [1, 2]
    path, v = reg.resolve("mlp", "latest")
    assert v == 2 and path.endswith(os.path.join("mlp", "2"))
    path1, _ = reg.resolve("mlp", 1)
    assert path1.endswith(os.path.join("mlp", "1"))
    assert reg.previous("mlp", 2) == 1 and reg.previous("mlp", 1) is None
    m = reg.verify("mlp", 2)
    assert m["content_hash"] and m["files"]      # hashes recorded + valid
    # versions are immutable
    with pytest.raises(ValueError, match="immutable"):
        reg.publish("mlp", d, version=1)


def test_registry_typed_errors(tmp_path):
    d, _, _ = _export_model(tmp_path)
    reg = ModelRegistry(str(tmp_path / "registry"))
    with pytest.raises(ValueError, match="no published versions"):
        reg.resolve("nope")
    reg.publish("mlp", d)
    with pytest.raises(ValueError, match="no published version 9"):
        reg.resolve("mlp", 9)
    with pytest.raises(ValueError, match="not a save_inference_model"):
        reg.publish("mlp", str(tmp_path))        # no __model__ there
    with pytest.raises(ValueError, match="one plain path component"):
        reg.resolve("a/b")


def test_registry_detects_corruption_and_torn_publish(tmp_path):
    d, _, _ = _export_model(tmp_path)
    reg = ModelRegistry(str(tmp_path / "registry"))
    v = reg.publish("mlp", d)
    path, _ = reg.resolve("mlp", v)
    # bit rot after publish: verify() re-hashes and raises typed
    npys = [f for f in os.listdir(path) if f.endswith(".npy")]
    with open(os.path.join(path, npys[0]), "r+b") as f:
        f.seek(-1, os.SEEK_END)
        f.write(b"\xff")
    with pytest.raises(ValueError, match="corrupt"):
        reg.verify("mlp", v)
    # a version dir WITHOUT its manifest (torn publish) is invisible
    torn = os.path.join(reg.model_dir("mlp"), "7")
    os.makedirs(torn)
    with open(os.path.join(torn, "__model__"), "w") as f:
        f.write("{}")
    assert reg.versions("mlp") == [v]
    # a resolvable version whose bundle is garbage fails the LOAD with
    # load_inference_model's typed error (the engine-side detection)
    bad_src = tmp_path / "bad"
    bad_src.mkdir()
    (bad_src / "__model__").write_text("not json at all")
    vb = reg.publish("mlp", str(bad_src))
    bad_path, _ = reg.resolve("mlp", vb)
    from paddle_tpu.serving import InferenceEngine
    with pytest.raises(ValueError, match="corrupt"):
        InferenceEngine(bad_path)


# ---------------------------------------------------------------------------
# ModelServer hot reload: zero downtime, version/reloads surfaced
# ---------------------------------------------------------------------------

def test_server_hot_reload_swaps_without_downtime(tmp_path):
    dA, xs, wantA = _export_model(tmp_path, "A")
    dB, _, wantB = _export_model(tmp_path, "B", weight_shift=0.25)
    assert not np.allclose(wantA, wantB)
    server = ModelServer(dA, buckets="1,2,4", max_delay_ms=1.0, version=1)
    server.start()
    errs = []
    stop = threading.Event()

    def hammer():
        with InferClient(server.address) as c:
            while not stop.is_set():
                try:
                    out = c.infer({"x": xs[:1]})[0]
                    # every answer is EXACTLY one model's — never a blend
                    if not (np.allclose(out, wantA[:1], rtol=1e-4,
                                        atol=1e-5)
                            or np.allclose(out, wantB[:1], rtol=1e-4,
                                           atol=1e-5)):
                        errs.append("blended answer")
                except Exception as e:
                    errs.append(e)

    ts = [threading.Thread(target=hammer) for _ in range(3)]
    for t in ts:
        t.start()
    time.sleep(0.1)                      # traffic established on A
    server.reload(dB, version=2)         # hot swap under load
    stop.set()
    for t in ts:
        t.join()
    assert not errs, errs[:3]
    with InferClient(server.address) as c:
        out = c.infer({"x": xs[:4]})
        np.testing.assert_allclose(out[0], wantB[:4], rtol=1e-5, atol=1e-6)
        st = c.stats()
        assert st["version"] == 2 and st["reloads"] == 1
        assert st["engine"]["hot_recompiles"] == 0   # warmed off hot path
        assert c.health()["version"] == 2
    server.shutdown()


def test_server_reload_failure_keeps_old_engine(tmp_path):
    dA, xs, wantA = _export_model(tmp_path, "A")
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "__model__").write_text("garbage")
    server = ModelServer(dA, buckets="1,2", max_delay_ms=1.0, version=1)
    server.start()
    with InferClient(server.address) as c:
        with pytest.raises(ValueError, match="corrupt"):
            server.reload(str(bad), version=2)    # typed, pre-swap failure
        out = c.infer({"x": xs[:2]})              # old engine still serves
        np.testing.assert_allclose(out[0], wantA[:2], rtol=1e-5, atol=1e-6)
        st = c.stats()
        assert st["version"] == 1 and st["reloads"] == 0
    server.shutdown()


# ---------------------------------------------------------------------------
# structured RPC error codes (replaces _OVERLOAD_MARK string sniffing)
# ---------------------------------------------------------------------------

def test_remote_error_carries_code_and_traceback(tmp_path):
    d, xs, _ = _export_model(tmp_path)
    server = ModelServer(d, buckets="1,2", max_delay_ms=1.0)
    server.start()
    with InferClient(server.address) as c:
        with pytest.raises(RemoteError) as ei:
            c.infer({"wrong_feed": xs[:1]})
        e = ei.value
        assert e.code == "ValueError"            # machine-checkable code
        assert "missing vars" in e.remote_message
        assert e.remote_traceback and "Traceback" in e.remote_traceback
        assert "missing vars" in str(e)          # message survives in str
    server.shutdown()


def test_overload_is_typed_via_code_not_message(tmp_path):
    """The overload mapping keys on the structured code, so a reworded
    message still re-raises typed — pinned by overloading through a
    handler whose message shares NO text with the type name."""
    d, xs, _ = _export_model(tmp_path)
    from paddle_tpu.serving.engine import InferenceEngine
    eng = InferenceEngine(d, buckets="1,2")
    release = threading.Event()
    inner = eng.infer

    def slow_infer(feed, fetch_list=None):
        release.wait(5.0)
        return inner(feed, fetch_list)

    eng.infer = slow_infer
    server = ModelServer(engine=eng, batching=True, queue_capacity=1,
                         max_delay_ms=1.0)
    server.start()
    outcomes = []

    def caller(i):
        with InferClient(server.address, retry=None) as c:
            try:
                c.infer({"x": xs[i:i + 1]})
                outcomes.append("ok")
            except ServerOverloaded:
                outcomes.append("overloaded")

    ts = [threading.Thread(target=caller, args=(i,)) for i in range(5)]
    for t in ts:
        t.start()
    deadline = time.monotonic() + 3.0
    while outcomes.count("overloaded") < 1 and time.monotonic() < deadline:
        time.sleep(0.005)
    release.set()
    for t in ts:
        t.join()
    assert outcomes.count("overloaded") >= 1
    server.shutdown()


# ---------------------------------------------------------------------------
# FleetClient router over in-process servers (fast: no child processes)
# ---------------------------------------------------------------------------

def _two_servers(d, **kw):
    s1 = ModelServer(d, buckets="1,2,4", max_delay_ms=1.0, **kw)
    s2 = ModelServer(d, buckets="1,2,4", max_delay_ms=1.0, **kw)
    s1.start()
    s2.start()
    return s1, s2


def test_router_balances_across_replicas(tmp_path):
    d, xs, want = _export_model(tmp_path)
    s1, s2 = _two_servers(d)
    with FleetClient([s1.address, s2.address]) as fc:
        for i in range(24):
            out = fc.infer({"x": xs[i % 8:i % 8 + 1]})
            np.testing.assert_allclose(out[0], want[i % 8:i % 8 + 1],
                                       rtol=1e-5, atol=1e-6)
        fs = fc.fleet_stats()
        assert fs["requests"] == 24 and fs["healthy"] == 2
        assert fs["p99_ms"] >= fs["p50_ms"] >= 0.0
        served = [r["server"]["wire"]["calls"].get("infer", {}).get(
            "count", 0) for r in fs["replicas"]]
        assert sum(served) == 24
        assert all(s > 0 for s in served), \
            f"power-of-two picks starved a replica: {served}"
        assert fs["engine"]["hot_recompiles"] == 0
    s1.shutdown()
    s2.shutdown()


def test_router_failover_eject_and_probation_readmit(tmp_path):
    d, xs, want = _export_model(tmp_path)
    s1, s2 = _two_servers(d)
    addr1 = s1.address
    with FleetClient([addr1, s2.address], probe_interval_ms=30,
                     probation_probes=2) as fc:
        for i in range(4):
            fc.infer({"x": xs[i:i + 1]})
        s1.kill()                        # crash replica 1
        for i in range(12):              # every request still answered
            out = fc.infer({"x": xs[i % 8:i % 8 + 1]})
            np.testing.assert_allclose(out[0], want[i % 8:i % 8 + 1],
                                       rtol=1e-5, atol=1e-6)
        fs = fc.fleet_stats(include_server_stats=False)
        assert fs["failovers"] >= 1 and fs["ejections"] >= 1
        assert fs["healthy"] == 1
        # restart on the SAME address: probation (2 consecutive healthy
        # probes at 30ms) re-admits it
        s1b = ModelServer(d, buckets="1,2,4", max_delay_ms=1.0,
                          address=addr1)
        s1b.start()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            fs = fc.fleet_stats(include_server_stats=False)
            if fs["healthy"] == 2:
                break
            time.sleep(0.05)
        assert fs["healthy"] == 2, fs
        # traffic reaches the re-admitted replica again
        before = s1b.stats()["wire"]["calls"].get("infer", {}).get(
            "count", 0)
        for i in range(16):
            fc.infer({"x": xs[i % 8:i % 8 + 1]})
        after = s1b.stats()["wire"]["calls"].get("infer", {}).get(
            "count", 0)
        assert after > before
        s1b.shutdown()
    s2.shutdown()


def test_router_overload_spills_then_surfaces_typed(tmp_path):
    d, xs, want = _export_model(tmp_path)
    from paddle_tpu.serving.engine import InferenceEngine

    def slow_server():
        eng = InferenceEngine(d, buckets="1,2")
        release = threading.Event()
        inner = eng.infer
        eng.infer = lambda feed, fetch_list=None: (
            release.wait(5.0), inner(feed, fetch_list))[1]
        s = ModelServer(engine=eng, batching=True, queue_capacity=1,
                        max_delay_ms=1.0)
        s.start()
        return s, release

    s1, rel1 = slow_server()            # saturates after ~2 requests
    s2 = ModelServer(d, buckets="1,2,4", max_delay_ms=1.0)
    s2.start()
    with FleetClient([s1.address, s2.address]) as fc:
        # hammer: requests landing on the wedged s1 beyond its queue spill
        # to s2 — no caller sees an overload while s2 has capacity
        results = []

        def one(i):
            try:
                out = fc.infer({"x": xs[i % 8:i % 8 + 1]})[0]
                np.testing.assert_allclose(out, want[i % 8:i % 8 + 1],
                                           rtol=1e-5, atol=1e-6)
                results.append("ok")
            except ServerOverloaded:
                results.append("overloaded")

        ts = [threading.Thread(target=one, args=(i,)) for i in range(10)]
        for t in ts:
            t.start()
        deadline = time.monotonic() + 5.0
        while len(results) < 8 and time.monotonic() < deadline:
            time.sleep(0.01)
        rel1.set()
        for t in ts:
            t.join()
        assert results.count("ok") >= 8, results
        fs = fc.fleet_stats(include_server_stats=False)
        if fs["spillovers"]:
            # spillover happened and was invisible to those callers
            assert results.count("ok") + results.count("overloaded") == 10
    s1.shutdown()
    s2.shutdown()

    # both replicas saturated -> the typed overload DOES surface
    s1, rel1 = slow_server()
    s2, rel2 = slow_server()
    with FleetClient([s1.address, s2.address]) as fc:
        outcomes = []

        def one2(i):
            try:
                fc.infer({"x": xs[i % 8:i % 8 + 1]})
                outcomes.append("ok")
            except ServerOverloaded:
                outcomes.append("overloaded")

        ts = [threading.Thread(target=one2, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        deadline = time.monotonic() + 5.0
        while outcomes.count("overloaded") < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        rel1.set()
        rel2.set()
        for t in ts:
            t.join()
        assert outcomes.count("overloaded") >= 1, outcomes
    s1.shutdown()
    s2.shutdown()


# ---------------------------------------------------------------------------
# FleetSupervisor end to end (spawned replica children — slower)
# ---------------------------------------------------------------------------

def _publish_two_versions(tmp_path):
    dA, xs, wantA = _export_model(tmp_path, "A")
    dB, _, wantB = _export_model(tmp_path, "B", weight_shift=0.25)
    reg = ModelRegistry(str(tmp_path / "registry"))
    v1 = reg.publish("mlp", dA)
    v2 = reg.publish("mlp", dB)
    return reg, (v1, v2), xs, (wantA, wantB)


def test_fleet_rolling_reload_keeps_n_minus_1_ready_and_rolls_back(
        tmp_path):
    """The rollout contract end to end on 2 spawned replicas: (1) traffic
    through a rolling reload sees zero failures and ≥N−1 replicas stay
    ready at every polled instant; (2) every replica lands on the target
    version with zero hot recompiles; (3) a corrupt canary version rolls
    back and the fleet stays on the good version throughout."""
    reg, (v1, v2), xs, (wantA, wantB) = _publish_two_versions(tmp_path)
    with FleetSupervisor(reg, "mlp", version=v1, n_replicas=2,
                         buckets="1,2,4", max_delay_ms=1.0) as sup:
        assert sup.wait_ready(240.0), "fleet never became ready"
        assert sup.version == v1
        with FleetClient(sup.addresses) as fc:
            out = fc.infer({"x": xs[:2]})
            np.testing.assert_allclose(out[0], wantA[:2], rtol=1e-5,
                                       atol=1e-6)
            errs = []
            stop = threading.Event()
            min_ready = [2]

            def hammer():
                while not stop.is_set():
                    try:
                        out = fc.infer({"x": xs[:1]})[0]
                        ok = (np.allclose(out, wantA[:1], rtol=1e-4,
                                          atol=1e-5)
                              or np.allclose(out, wantB[:1], rtol=1e-4,
                                             atol=1e-5))
                        if not ok:
                            errs.append("wrong answer")
                    except Exception as e:
                        errs.append(e)

            def poll_ready():
                while not stop.is_set():
                    min_ready[0] = min(min_ready[0], sup.ready_count())
                    time.sleep(0.05)

            ts = [threading.Thread(target=hammer) for _ in range(2)]
            ts.append(threading.Thread(target=poll_ready))
            for t in ts:
                t.start()
            try:
                got = sup.rolling_reload(v2, wait_timeout=240.0)
            finally:
                stop.set()
                for t in ts:
                    t.join()
            assert got == v2 and sup.version == v2
            assert not errs, f"requests failed during rollout: {errs[:3]}"
            assert min_ready[0] >= 1, \
                f"rollout dropped below N-1 ready: {min_ready[0]}"
            stats = sup.replica_stats()
            for i, st in stats.items():
                assert st is not None
                assert st["version"] == v2, (i, st["version"])
                assert st["engine"]["hot_recompiles"] == 0
                assert st["reloads"] >= 1
            # post-rollout answers are the NEW model's
            out = fc.infer({"x": xs[:3]})
            np.testing.assert_allclose(out[0], wantB[:3], rtol=1e-5,
                                       atol=1e-6)

            # fleet-wide obs scrape: every replica answers the built-in
            # ``metrics`` RPC, and the merged view carries at least the
            # per-replica engine compile counts replica_stats reported
            fm = sup.fleet_metrics()
            assert all(s is not None for s in fm["replicas"].values())
            eng = fm["merged"]["paddle_tpu_engine_compiles"]
            merged_compiles = sum(v["value"] for v in eng["values"])
            assert merged_compiles >= sum(st["engine"]["compiles"]
                                          for st in stats.values())
            json.dumps(fm)     # the whole scrape is wire-safe
            # accelerator-identity stamps: device count + kind ride the
            # scrape so fleet views are comparable across hosts
            assert fm["n_devices"] == jax.device_count()
            assert fm["device_kind"] == str(getattr(
                jax.devices()[0], "device_kind", jax.devices()[0].platform))

            # ---- failed canary: corrupt v3 rolls back, fleet untouched
            bad_src = tmp_path / "bad"
            bad_src.mkdir()
            (bad_src / "__model__").write_text("not a model")
            v3 = reg.publish("mlp", str(bad_src))
            with pytest.raises(RuntimeError, match="canary"):
                sup.rolling_reload(v3, wait_timeout=240.0)
            assert sup.version == v2           # target never advanced
            for i in range(2):
                h = sup.replica_health(i)
                assert h is not None and h["version"] == v2, (i, h)
            out = fc.infer({"x": xs[:1]})      # still serving v2 answers
            np.testing.assert_allclose(out[0], wantB[:1], rtol=1e-5,
                                       atol=1e-6)


def test_fleet_replica_dies_mid_request_failover_restart_rejoin(tmp_path):
    """The satellite fault case: a FaultPlan kills replica 0's server mid
    ``infer`` — the FleetClient answers every request from the surviving
    replica (zero failures), the supervisor restarts the dead child from
    the registry's current version, and the router re-admits it through
    the probation path."""
    reg, (v1, _v2), xs, (wantA, _) = _publish_two_versions(tmp_path)
    # replica 0 dies BEFORE serving its 2nd infer; applied to the FIRST
    # spawn only (the restarted child must come back clean and rejoin)
    plan = FaultPlan().die("infer", 1, before=True)
    with FleetSupervisor(reg, "mlp", version=v1, n_replicas=2,
                         buckets="1,2,4", max_delay_ms=1.0,
                         fault_plans={0: plan}) as sup:
        assert sup.wait_ready(240.0)
        with FleetClient(sup.addresses, probe_interval_ms=50,
                         probation_probes=2,
                         retry=RetryPolicy(max_retries=10,
                                           backoff_base_s=0.05,
                                           backoff_max_s=0.5)) as fc:
            # sequential single-row infers: the random picks route ~half
            # to replica 0, whose 2nd infer triggers the die — the
            # failover must keep every answer correct
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                out = fc.infer({"x": xs[:1]})
                np.testing.assert_allclose(out[0], wantA[:1], rtol=1e-5,
                                           atol=1e-6)
                if fc.fleet_stats(
                        include_server_stats=False)["failovers"] >= 1:
                    break
            fs = fc.fleet_stats(include_server_stats=False)
            assert fs["failovers"] >= 1 and fs["ejections"] >= 1, fs
            # the supervisor restarts replica 0 from the registry's
            # current version; probation re-admits it
            deadline = time.monotonic() + 240.0
            while time.monotonic() < deadline:
                fs = fc.fleet_stats(include_server_stats=False)
                if fs["healthy"] == 2:
                    break
                time.sleep(0.25)
            assert fs["healthy"] == 2, f"replica never rejoined: {fs}"
            assert sup.restarts[0] >= 1
            h = sup.replica_health(0)
            assert h is not None and h["version"] == v1   # current version
            # and it serves correctly again
            for _ in range(8):
                out = fc.infer({"x": xs[:1]})
                np.testing.assert_allclose(out[0], wantA[:1], rtol=1e-5,
                                           atol=1e-6)


# ---------------------------------------------------------------------------
# ChildSupervisor: the shared supervision helper (regression net for the
# PserverSupervisor refactor, with cheap numpy-only fork children)
# ---------------------------------------------------------------------------

def _echo_child(address, token):
    from paddle_tpu.distributed.rpc import RpcServer

    class H:
        def stats(self):
            return {"token": token, "pid": os.getpid()}

    RpcServer(H(), tuple(address)).serve_forever()


def _suicide_child(address):
    return                               # exits immediately: crash loop


class _EchoSupervisor(ChildSupervisor):
    def _child_spec(self, i):
        return _echo_child, (self.addresses[i], i)


class _CrashLoopSupervisor(ChildSupervisor):
    def _child_spec(self, i):
        return _suicide_child, (self.addresses[i],)


def test_child_supervisor_restarts_on_same_address():
    from paddle_tpu.distributed.rpc import RpcClient
    with _EchoSupervisor(2, heartbeat_interval_s=0.1) as sup:
        assert sup.wait_ready(20.0)
        addr0 = sup.addresses[0]
        c = RpcClient(addr0, timeout=5.0, retry=RetryPolicy(
            max_retries=25, backoff_base_s=0.05, backoff_max_s=0.25))
        pid_before = c.call("stats")["pid"]
        sup.kill(0)
        # the retrying client reconnects straight through the restart to
        # the SAME address — a NEW process answering there
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            try:
                if c.call("stats")["pid"] != pid_before:
                    break
            except Exception:
                pass
            time.sleep(0.05)
        assert c.call("stats")["pid"] != pid_before
        assert sup.addresses[0] == addr0 and sup.restarts[0] == 1
        assert sup.child_alive(0)
        c.close()


def test_child_supervisor_gives_up_after_max_restarts():
    with _CrashLoopSupervisor(1, heartbeat_interval_s=0.05,
                              max_restarts=2) as sup:
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if sup.restarts[0] >= 2 and not sup.child_alive(0):
                break
            time.sleep(0.05)
        assert sup.restarts[0] == 2       # capped, not a hot loop
        assert not sup.child_alive(0)


def test_pserver_supervisor_rides_shared_helper():
    """Structural pin for the dedup satellite: PserverSupervisor IS a
    ChildSupervisor (same loop the fleet reuses), its heartbeat stays on
    the pserver ``stats`` surface, its children keep the fixed-address +
    per-shard-checkpoint spec, and the startup grace that the fleet needs
    stays ZERO here (original wedge-detection timing unchanged). The
    behavioral pin is test_fault_injection.py's kill-restore e2e."""
    import paddle_tpu.distributed.launch as launch
    assert issubclass(PserverSupervisor, ChildSupervisor)
    sup = PserverSupervisor.__new__(PserverSupervisor)
    sup._cfg = {}
    sup._ckpt_dir = "/tmp/x"
    sup.addresses = [("127.0.0.1", 1234)]
    target, args = sup._child_spec(0)
    assert target is launch._pserver_child
    assert args[0] == ("127.0.0.1", 1234)
    assert args[1] == sup.checkpoint_path(0)
    import inspect
    sig = inspect.signature(ChildSupervisor.__init__)
    assert sig.parameters["startup_grace_s"].default == 0.0
    assert sig.parameters["mp_start_method"].default == "fork"
