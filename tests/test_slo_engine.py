"""SLO engine (paddle_tpu/obs/slo.py): declarative rule validation and
wire form, reducer/burn math, multi-window breach semantics, the
background monitor + breach counters/findings, the process-default
install surface through ``ModelServer.health()`` (a seeded breach
appears within one evaluation window), and the one-shot fleet-view
evaluation ``FleetSupervisor.fleet_metrics`` runs (which must not
pollute the background monitor's registry series)."""

import json
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core.flags import set_flags
from paddle_tpu.obs import metrics as obsm
from paddle_tpu.obs import slo as obslo
from paddle_tpu.obs.slo import SloMonitor, SloRule


def _hist_snapshot(name, values, labels=("instance",), lv="i1"):
    """A registry-shaped snapshot holding one histogram family."""
    durs = sorted(values)
    return {name: {
        "type": "histogram", "help": "", "labels": list(labels),
        "values": [{"labels": {labels[0]: lv}, "count": len(durs),
                    "window": len(durs),
                    "p50_ms": durs[len(durs) // 2],
                    "p99_ms": durs[-1], "max_ms": durs[-1]}],
    }}


def _gauge_snapshot(name, by_instance):
    return {name: {
        "type": "gauge", "help": "", "labels": ["instance"],
        "values": [{"labels": {"instance": k}, "value": v}
                   for k, v in by_instance.items()],
    }}


# ---------------------------------------------------------------------------
# rule validation + wire form
# ---------------------------------------------------------------------------

def test_rule_validation_and_dict_round_trip():
    r = SloRule("p99", "paddle_tpu_serving_request_seconds", 50.0,
                reducer="p99_ms", labels={"instance": "x"},
                windows=((5.0, 1.0), (60.0, 0.5)), description="d")
    r2 = SloRule.from_dict(r.to_dict())
    assert r2.to_dict() == r.to_dict()
    json.dumps(r.to_dict())                      # crosses the spawn wire

    with pytest.raises(ValueError, match="objective"):
        SloRule("bad", "m", 0.0)
    with pytest.raises(ValueError, match="reducer"):
        SloRule("bad", "m", 1.0, reducer="p42_ms")
    with pytest.raises(ValueError, match="agg"):
        SloRule("bad", "m", 1.0, agg="median")
    with pytest.raises(ValueError, match="at least"):
        SloRule("bad", "m", 1.0, windows=())
    with pytest.raises(ValueError, match="window"):
        SloRule("bad", "m", 1.0, windows=((0.0, 1.0),))
    with pytest.raises(ValueError, match="unknown fields"):
        SloRule.from_dict({"name": "x", "metric": "m", "objective": 1.0,
                           "bogus": 1})
    with pytest.raises(ValueError, match="duplicate"):
        SloMonitor([SloRule("a", "m", 1.0), SloRule("a", "m", 2.0)])


def test_rule_measure_reducers_selectors_and_agg():
    snap = _gauge_snapshot("paddle_tpu_test_slo_depth",
                           {"a": 3.0, "b": 7.0})
    r_max = SloRule("d", "paddle_tpu_test_slo_depth", 5.0,
                    reducer="value")
    r_sum = SloRule("d", "paddle_tpu_test_slo_depth", 5.0,
                    reducer="value", agg="sum")
    r_sel = SloRule("d", "paddle_tpu_test_slo_depth", 5.0,
                    reducer="value", labels={"instance": "a"})
    assert r_max.measure(snap) == 7.0            # worst instance
    assert r_sum.measure(snap) == 10.0
    assert r_sel.measure(snap) == 3.0            # label-filtered
    # absent family / no matching child measures None (burn 0)
    assert r_max.measure({}) is None
    assert r_sel.measure(_gauge_snapshot("paddle_tpu_test_slo_depth",
                                         {"z": 9.0})) is None
    h = _hist_snapshot("paddle_tpu_test_slo_lat", [1.0, 2.0, 40.0])
    assert SloRule("l", "paddle_tpu_test_slo_lat", 10.0,
                   reducer="p99_ms").measure(h) == 40.0


# ---------------------------------------------------------------------------
# burn-rate evaluation + multi-window breach semantics
# ---------------------------------------------------------------------------

def test_single_window_breach_transition_and_recovery():
    mon = SloMonitor([SloRule("depth", "paddle_tpu_test_slo_depth", 4.0,
                              reducer="value", windows=((1.0, 1.0),))],
                     emit_metrics=False)
    ok = _gauge_snapshot("paddle_tpu_test_slo_depth", {"a": 2.0})
    hot = _gauge_snapshot("paddle_tpu_test_slo_depth", {"a": 8.0})
    st = mon.evaluate_once(ok, now=100.0)
    assert st["depth"]["ok"] and st["depth"]["burn"] == 0.5
    # breach fires on the ok->breach TRANSITION only, and re-arms after
    # recovery
    st = mon.evaluate_once(hot, now=101.5)       # old sample aged out
    assert not st["depth"]["ok"] and st["depth"]["breaches"] == 1
    st = mon.evaluate_once(hot, now=101.6)
    assert st["depth"]["breaches"] == 1          # no re-count while hot
    st = mon.evaluate_once(ok, now=103.0)
    assert st["depth"]["ok"]
    st = mon.evaluate_once(hot, now=105.0)
    assert st["depth"]["breaches"] == 2          # re-armed
    f = mon.findings()
    assert len(f) == 2 and f[0].rule == "depth" and f[0].burn == 2.0
    json.dumps(f[0].as_dict())


def test_multi_window_requires_every_window_burning():
    # short window (1s) + long window (60s), both threshold 1.0: one
    # hot sample trips the short window alone — the classic pairing
    # where a spike must NOT breach until the burn is sustained
    cool = _hist_snapshot("paddle_tpu_test_slo_lat", [1.0])    # burn 0.1
    hot = _hist_snapshot("paddle_tpu_test_slo_lat", [100.0])   # burn 10
    mon = SloMonitor([SloRule("lat", "paddle_tpu_test_slo_lat", 10.0,
                              reducer="p99_ms",
                              windows=((1.0, 1.0), (60.0, 1.0)))],
                     emit_metrics=False)
    t = 1000.0
    for i in range(30):
        mon.evaluate_once(cool, now=t + i)
    st = mon.evaluate_once(hot, now=t + 30)
    # the 1s window (the hot sample + the boundary-inclusive last cool
    # one) burns well past threshold; the 60s average stays cool
    assert st["lat"]["windows"]["1s"] > 1.0
    assert st["lat"]["windows"]["60s"] < 1.0
    assert st["lat"]["ok"], "one spike must not breach the long window"
    # sustained burn trips BOTH windows
    for i in range(31, 31 + 40):
        st = mon.evaluate_once(hot, now=t + i)
    assert not st["lat"]["ok"] and st["lat"]["breaches"] == 1


def test_rate_reducer_uses_counter_deltas():
    def counter_snap(v):
        return {"paddle_tpu_test_slo_errs": {
            "type": "counter", "help": "", "labels": [],
            "values": [{"labels": {}, "value": v}]}}

    mon = SloMonitor([SloRule("errs", "paddle_tpu_test_slo_errs", 5.0,
                              reducer="rate", windows=((10.0, 1.0),))],
                     emit_metrics=False)
    st = mon.evaluate_once(counter_snap(100), now=10.0)
    assert st["errs"]["value"] is None           # no delta yet
    st = mon.evaluate_once(counter_snap(120), now=12.0)
    assert st["errs"]["value"] == pytest.approx(10.0)   # 20 in 2s
    assert st["errs"]["burn"] == pytest.approx(2.0)
    # counter reset (restarted process) clamps to 0, never negative
    st = mon.evaluate_once(counter_snap(5), now=14.0)
    assert st["errs"]["value"] == 0.0


def test_background_monitor_emits_series_and_findings():
    fam = obsm.REGISTRY.gauge("paddle_tpu_test_slo_bg",
                              labels=("instance",))
    fam.labels(instance="x").set(50.0)
    mon = SloMonitor([SloRule("bg", "paddle_tpu_test_slo_bg", 10.0,
                              reducer="value", windows=((0.5, 1.0),))],
                     interval_s=0.05)
    mon.start()
    try:
        deadline = time.monotonic() + 10.0
        while mon.breach_count() == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert mon.breach_count() == 1
        hs = mon.health_section()
        assert hs["ok"] is False and hs["evaluations"] >= 1
        assert hs["recent_breaches"][-1]["rule"] == "bg"
        json.dumps(hs)
        # the registry series moved: burn gauge set, breach counter
        # bumped — the scrape-visible half of the verdict
        burn = obsm.REGISTRY.get("paddle_tpu_slo_burn_rate")
        assert burn.labels(rule="bg", window="0.5s").value \
            == pytest.approx(5.0)
        breaches = obsm.REGISTRY.get("paddle_tpu_slo_breaches")
        assert breaches.labels(rule="bg").value == 1
    finally:
        mon.stop()
    assert not mon.running()


def test_on_breach_callback_fires_outside_lock():
    fired = []
    fam = obsm.REGISTRY.gauge("paddle_tpu_test_slo_cb")
    fam.child().set(99.0)
    mon = SloMonitor([SloRule("cb", "paddle_tpu_test_slo_cb", 1.0,
                              reducer="value", windows=((0.5, 1.0),))],
                     on_breach=lambda f: fired.append(f),
                     emit_metrics=False)
    mon.evaluate_once()
    assert len(fired) == 1 and fired[0].rule == "cb"
    mon.evaluate_once()
    assert len(fired) == 1                       # transition only


# ---------------------------------------------------------------------------
# install surface: ModelServer.health() + fleet one-shot view
# ---------------------------------------------------------------------------

@pytest.fixture
def _fast_slo_interval():
    set_flags({"obs_slo_interval_s": 0.05})
    yield
    set_flags({"obs_slo_interval_s": 1.0})
    obslo.install(None)


def _export_model(tmp_path, dim=4, classes=2):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[dim])
        y = fluid.layers.fc(input=x, size=classes, act="softmax")
    exe = fluid.Executor()
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    d = str(tmp_path / "model")
    fluid.io.save_inference_model(d, ["x"], [y], exe, main, scope=scope)
    return d, dim


def test_seeded_breach_appears_in_model_server_health(tmp_path,
                                                      _fast_slo_interval):
    """The acceptance shape: an objective set BELOW anything measurable
    flips paddle_tpu_slo_breaches and shows in health() within one
    evaluation window."""
    from paddle_tpu.serving import InferClient, ModelServer

    d, dim = _export_model(tmp_path)
    breaches_before = int(sum(
        c.value for c in obsm.REGISTRY.get(
            "paddle_tpu_slo_breaches").children().values()))
    server = ModelServer(d, buckets="1,2", slo_rules=[
        {"name": "seeded_latency", "objective": 1e-6, "reducer": "p99_ms",
         "metric": "paddle_tpu_serving_request_seconds",
         "windows": [[0.3, 1.0]]}])
    server.start()
    try:
        with InferClient(server.address) as c:
            c.infer({"x": np.zeros((1, dim), np.float32)})
            deadline = time.monotonic() + 10.0
            h = c.health()
            while time.monotonic() < deadline:
                h = c.health()
                if h.get("slo", {}).get("rules", {}).get(
                        "seeded_latency", {}).get("breaches", 0):
                    break
                time.sleep(0.05)
        assert h["slo"]["ok"] is False
        rule = h["slo"]["rules"]["seeded_latency"]
        assert rule["breaches"] >= 1 and rule["value"] > rule["objective"]
        assert h["slo"]["recent_breaches"][-1]["rule"] == "seeded_latency"
        json.dumps(h)
        breaches_after = int(sum(
            c.value for c in obsm.REGISTRY.get(
                "paddle_tpu_slo_breaches").children().values()))
        assert breaches_after > breaches_before
    finally:
        server.shutdown()
    # the server-owned monitor stopped and uninstalled with the server
    assert obslo.installed() is None


def test_fleet_one_shot_view_does_not_pollute_registry(tmp_path):
    """fleet_metrics-style one-shot evaluation over a merged snapshot:
    fresh throwaway state, emit_metrics=False — the background
    monitor's paddle_tpu_slo_* series must not move."""
    rule = SloRule("oneshot", "paddle_tpu_test_slo_fleet", 5.0,
                   reducer="value", windows=((60.0, 1.0),))
    merged = obsm.merge_snapshots([
        _gauge_snapshot("paddle_tpu_test_slo_fleet", {"r0": 4.0}),
        _gauge_snapshot("paddle_tpu_test_slo_fleet", {"r1": 9.0}),
    ])
    before = obsm.REGISTRY.get("paddle_tpu_slo_breaches").snapshot()
    view = SloMonitor([rule.to_dict()],
                      emit_metrics=False).evaluate_once(merged)
    assert view["oneshot"]["ok"] is False        # worst replica judged
    assert view["oneshot"]["value"] == 9.0
    assert obsm.REGISTRY.get("paddle_tpu_slo_breaches").snapshot() \
        == before
    # no burn series for the one-shot rule either
    burn = obsm.REGISTRY.get("paddle_tpu_slo_burn_rate")
    assert not any(k[0] == "oneshot" for k in burn.children())


def test_fleet_metrics_marks_rate_rules_unmeasurable():
    """A rate rule needs two samples for a counter delta; a fresh
    one-shot fleet view must surface it as unmeasurable, never as a
    falsely-green burn-0 verdict (other reducers are judged)."""
    import threading

    from paddle_tpu.serving.fleet import FleetSupervisor

    fam = obsm.REGISTRY.gauge("paddle_tpu_test_slo_fleetrate")
    fam.child().set(9.0)
    mon = SloMonitor([
        SloRule("gauge_rule", "paddle_tpu_test_slo_fleetrate", 1.0,
                reducer="value", windows=((60.0, 1.0),)),
        SloRule("rate_rule", "paddle_tpu_test_slo_fleetrate", 1.0,
                reducer="rate", windows=((60.0, 1.0),)),
    ], emit_metrics=False)
    mon.install()
    try:
        sup = FleetSupervisor.__new__(FleetSupervisor)  # no children
        sup.addresses = []
        sup._version = 1
        sup._version_lock = threading.Lock()
        view = sup.fleet_metrics(include_local=True)["slo"]["fleet"]
        assert view["gauge_rule"]["ok"] is False     # judged one-shot
        assert view["rate_rule"]["ok"] is None
        assert "unmeasurable" in view["rate_rule"]
    finally:
        obslo.install(None)
