"""Activation op tests (reference tests/unittests/test_activation_op.py)."""

import numpy as np
import pytest

from op_test import OpTest


def _sigmoid(x):
    return 1 / (1 + np.exp(-x))


CASES = {
    "sigmoid": (lambda x: _sigmoid(x), (-1, 1)),
    "logsigmoid": (lambda x: np.log(_sigmoid(x)), (-1, 1)),
    "exp": (np.exp, (-1, 1)),
    "relu": (lambda x: np.maximum(x, 0), (-1, 1)),
    "tanh": (np.tanh, (-1, 1)),
    "tanh_shrink": (lambda x: x - np.tanh(x), (0.5, 2)),
    "sqrt": (np.sqrt, (0.1, 1)),
    "abs": (np.abs, (0.5, 2)),
    "ceil": (np.ceil, (-1, 1)),
    "floor": (np.floor, (-1, 1)),
    "round": (np.round, (-1, 1)),
    "reciprocal": (lambda x: 1 / x, (0.5, 2)),
    "log": (np.log, (0.5, 2)),
    "square": (np.square, (-1, 1)),
    "softplus": (lambda x: np.log(1 + np.exp(x)), (-1, 1)),
    "softsign": (lambda x: x / (1 + np.abs(x)), (-1, 1)),
    "soft_relu": (lambda x: np.log(1 + np.exp(np.clip(x, -40, 40))), (-1, 1)),
    # reference test_activation_op.py TestRelu6/TestSwish/TestHardShrink/
    # TestSoftShrink/TestThresholdedRelu (default attrs)
    "relu6": (lambda x: np.clip(x, 0.0, 6.0), (-2, 8)),
    "swish": (lambda x: x / (1 + np.exp(-x)), (-1, 1)),
    "hard_shrink": (lambda x: np.where(np.abs(x) > 0.5, x, 0.0), (-2, 2)),
    "softshrink": (lambda x: np.where(x > 0.5, x - 0.5,
                                      np.where(x < -0.5, x + 0.5, 0.0)),
                   (-2, 2)),
    "thresholded_relu": (lambda x: np.where(x > 1.0, x, 0.0), (-2, 3)),
    # round-5 runtime-dispatch audit: these three registered grads never
    # executed (reference TestBRelu/TestSTanh/TestHardSigmoid, default attrs)
    "brelu": (lambda x: np.clip(x, 0.0, 24.0), (-4, 30)),
    "stanh": (lambda x: 1.7159 * np.tanh(0.67 * x), (-2, 2)),
    "hard_sigmoid": (lambda x: np.clip(0.2 * x + 0.5, 0.0, 1.0), (-4, 4)),
}

GRAD_SKIP = {"ceil", "floor", "round"}  # zero-gradient ops

# non-differentiable points per op: inputs are nudged off them before the
# finite-difference grad check (reference op_tests do the same via x[...]= )
KINKS = {"abs": [0.0], "relu": [0.0], "relu6": [0.0, 6.0],
         "hard_shrink": [-0.5, 0.5], "softshrink": [-0.5, 0.5],
         "thresholded_relu": [1.0], "brelu": [0.0, 24.0],
         "hard_sigmoid": [-2.5, 2.5]}


def _nudge(x, op_name, margin=0.05):
    for k in KINKS.get(op_name, ()):
        near = np.abs(x - k) < margin
        x[near] = k + 4 * margin
    return x


@pytest.mark.parametrize("op_name", sorted(CASES))
def test_activation_output(op_name):
    fn, (lo, hi) = CASES[op_name]
    t = OpTest()
    t.op_type = op_name
    x = np.random.uniform(lo, hi, (4, 6)).astype("float32")
    t.inputs = {"X": x}
    t.attrs = {}
    t.outputs = {"Out": fn(x)}
    # XLA CPU's vectorized transcendental approximations differ from numpy's
    # libm at the ~1e-4 level; arithmetic ops stay at the strict default.
    t.check_output(atol=5e-4, rtol=2e-3)


@pytest.mark.parametrize("op_name", sorted(set(CASES) - GRAD_SKIP))
def test_activation_grad(op_name):
    fn, (lo, hi) = CASES[op_name]
    t = OpTest()
    t.op_type = op_name
    x = _nudge(np.random.uniform(lo, hi, (3, 4)).astype("float32"), op_name,
               margin=0.1)
    t.inputs = {"X": x}
    t.attrs = {}
    t.outputs = {"Out": fn(x)}
    t.check_grad(["X"], "Out", max_relative_error=0.01)


def test_leaky_relu():
    t = OpTest()
    t.op_type = "leaky_relu"
    x = np.random.uniform(-1, 1, (4, 5)).astype("float32")
    x[np.abs(x) < 0.1] = 0.5
    t.inputs = {"X": x}
    t.attrs = {"alpha": 0.1}
    t.outputs = {"Out": np.where(x >= 0, x, 0.1 * x)}
    t.check_output()
    t.check_grad(["X"], "Out")


def test_elu():
    t = OpTest()
    t.op_type = "elu"
    x = np.random.uniform(-1, 1, (4, 5)).astype("float32")
    x[np.abs(x) < 0.1] = 0.5
    t.inputs = {"X": x}
    t.attrs = {"alpha": 0.5}
    t.outputs = {"Out": np.where(x >= 0, x, 0.5 * (np.exp(x) - 1))}
    t.check_output()
    t.check_grad(["X"], "Out", max_relative_error=0.02)


def test_pow_op():
    t = OpTest()
    t.op_type = "pow"
    x = np.random.uniform(0.5, 2, (4, 5)).astype("float32")
    t.inputs = {"X": x}
    t.attrs = {"factor": 3.0}
    t.outputs = {"Out": np.power(x, 3.0)}
    t.check_output()
    t.check_grad(["X"], "Out", max_relative_error=0.02)


def test_brelu():
    t = OpTest()
    t.op_type = "brelu"
    x = np.random.uniform(-3, 3, (4, 5)).astype("float32")
    t.inputs = {"X": x}
    t.attrs = {"t_min": -1.0, "t_max": 1.0}
    t.outputs = {"Out": np.clip(x, -1.0, 1.0)}
    t.check_output()


def test_hard_sigmoid():
    t = OpTest()
    t.op_type = "hard_sigmoid"
    x = np.random.uniform(-3, 3, (4, 5)).astype("float32")
    t.inputs = {"X": x}
    t.attrs = {"slope": 0.2, "offset": 0.5}
    t.outputs = {"Out": np.clip(0.2 * x + 0.5, 0, 1)}
    t.check_output()
