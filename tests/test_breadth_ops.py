"""OpTests for the round-4 breadth ops.

Reference tests: python/paddle/fluid/tests/unittests/test_{expand,pad,crop,
label_smooth,minus,l1_norm,conv_shift,modified_huber_loss,
fill_constant_batch_size_like,uniform_random_batch_size_like,
gaussian_random_batch_size_like,conv3d_transpose,pool_max,
positive_negative_pair,average_accumulates,detection_map}_op.py.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from op_test import OpTest

layers = fluid.layers


class TestExpand(OpTest):
    op_type = "expand"

    def setup(self):
        rng = np.random.RandomState(0)
        x = rng.rand(2, 3, 4).astype("float32")
        times = [2, 1, 3]
        self.inputs = {"X": x}
        self.attrs = {"expand_times": times}
        self.outputs = {"Out": np.tile(x, times)}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X"], "Out")


class TestPad(OpTest):
    op_type = "pad"

    def setup(self):
        rng = np.random.RandomState(1)
        x = rng.rand(3, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"paddings": [1, 2, 0, 3], "pad_value": 0.5}
        self.outputs = {"Out": np.pad(x, [(1, 2), (0, 3)],
                                      constant_values=0.5)}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X"], "Out")


class TestCrop(OpTest):
    op_type = "crop"

    def setup(self):
        rng = np.random.RandomState(2)
        x = rng.rand(5, 6).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"offsets": [1, 2], "shape": [2, 3]}
        self.outputs = {"Out": x[1:3, 2:5]}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X"], "Out")


class TestLabelSmooth(OpTest):
    op_type = "label_smooth"

    def setup(self, with_prior=False):
        rng = np.random.RandomState(3)
        eps = 0.1
        label = np.zeros((4, 6), "float32")
        label[np.arange(4), rng.randint(0, 6, 4)] = 1.0
        self.inputs = {"X": label}
        self.attrs = {"epsilon": eps}
        if with_prior:
            prior = rng.dirichlet(np.ones(6)).astype("float32")
            self.inputs["PriorDist"] = prior
            self.outputs = {"Out": (1 - eps) * label + eps * prior}
        else:
            self.outputs = {"Out": (1 - eps) * label + eps / 6.0}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_output_prior(self):
        self.setup(with_prior=True)
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X"], "Out")


class TestMinus(OpTest):
    op_type = "minus"

    def setup(self):
        rng = np.random.RandomState(4)
        x = rng.rand(3, 4).astype("float32")
        y = rng.rand(3, 4).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x - y}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X", "Y"], "Out")


class TestL1Norm(OpTest):
    op_type = "l1_norm"

    def setup(self):
        rng = np.random.RandomState(5)
        # keep |x| away from 0 so the finite-difference grad is stable
        x = rng.uniform(0.2, 1.0, (4, 5)).astype("float32") \
            * rng.choice([-1, 1], (4, 5)).astype("float32")
        self.inputs = {"X": x}
        self.outputs = {"Out": np.abs(x).sum()}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X"], "Out", numeric_grad_delta=1e-3)


def _conv_shift_np(x, y):
    b, w = x.shape
    m = y.shape[1]
    out = np.zeros_like(x)
    for i in range(w):
        for j in range(m):
            out[:, i] += x[:, (i + j - m // 2) % w] * y[:, j]
    return out


class TestConvShift(OpTest):
    op_type = "conv_shift"

    def setup(self):
        rng = np.random.RandomState(6)
        x = rng.rand(3, 8).astype("float32")
        y = rng.rand(3, 3).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": _conv_shift_np(x, y)}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X", "Y"], "Out")


class TestModifiedHuberLoss(OpTest):
    op_type = "modified_huber_loss"

    def setup(self):
        rng = np.random.RandomState(7)
        x = rng.uniform(-3, 3, (10, 1)).astype("float32")
        y = rng.randint(0, 2, (10, 1)).astype("float32")
        inter = x * (2 * y - 1)
        loss = np.where(inter < -1, -4 * inter,
                        np.where(inter < 1, (1 - inter) ** 2, 0.0))
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"IntermediateVal": inter,
                        "Out": loss.astype("float32")}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X"], "Out", max_relative_error=0.01)


def test_uniform_random_batch_size_like():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ref = layers.data("ref", shape=[7])
        block = main.global_block()
        block.create_var(name="u")
        block.append_op("uniform_random_batch_size_like",
                        {"Input": ["ref"]}, {"Out": ["u"]},
                        {"shape": [-1, 11], "min": 2.0, "max": 3.0})
        block.create_var(name="g")
        block.append_op("gaussian_random_batch_size_like",
                        {"Input": ["ref"]}, {"Out": ["g"]},
                        {"shape": [-1, 5], "mean": 10.0, "std": 0.1})
    exe = fluid.Executor(fluid.CPUPlace())
    u, g = exe.run(main, feed={"ref": np.zeros((4, 7), "float32")},
                   fetch_list=["u", "g"])
    assert u.shape == (4, 11) and (u >= 2.0).all() and (u <= 3.0).all()
    assert g.shape == (4, 5) and abs(g.mean() - 10.0) < 0.5


def _conv3d_transpose_np(x, w, stride):
    n, c, d, h, wd = x.shape
    _, m, kd, kh, kw = w.shape
    od = (d - 1) * stride + kd
    oh = (h - 1) * stride + kh
    ow = (wd - 1) * stride + kw
    out = np.zeros((n, m, od, oh, ow), "float64")
    for b in range(n):
        for ci in range(c):
            for z in range(d):
                for i in range(h):
                    for j in range(wd):
                        out[b, :, z * stride:z * stride + kd,
                            i * stride:i * stride + kh,
                            j * stride:j * stride + kw] += \
                            x[b, ci, z, i, j] * w[ci]
    return out.astype("float32")


class TestConv3dTranspose(OpTest):
    op_type = "conv3d_transpose"

    def setup(self):
        rng = np.random.RandomState(8)
        x = rng.rand(2, 3, 2, 3, 3).astype("float32")
        w = rng.rand(3, 4, 2, 2, 2).astype("float32")
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [2, 2, 2], "paddings": [0, 0, 0]}
        self.outputs = {"Output": _conv3d_transpose_np(x, w, 2)}

    def test_output(self):
        self.setup()
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.setup()
        self.check_grad(["Input", "Filter"], "Output",
                        max_relative_error=0.02)


def test_max_pool3d_with_index():
    rng = np.random.RandomState(9)
    x = rng.rand(2, 3, 4, 4, 4).astype("float32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = layers.data("x", shape=[3, 4, 4, 4])
        out, mask = layers.max_pool3d_with_index(xv, pool_size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    got, got_mask = exe.run(main, feed={"x": x},
                            fetch_list=[out, mask])
    # numpy reference
    exp = x.reshape(2, 3, 2, 2, 2, 2, 2, 2).transpose(
        0, 1, 2, 4, 6, 3, 5, 7).reshape(2, 3, 2, 2, 2, 8).max(-1)
    np.testing.assert_allclose(got, exp, rtol=1e-6)
    # mask points at the argmax element
    d = h = w = 4
    for b in range(2):
        for c in range(3):
            for z in range(2):
                for i in range(2):
                    for j in range(2):
                        flat = int(got_mask[b, c, z, i, j])
                        zz, rest = flat // (h * w), flat % (h * w)
                        ii, jj = rest // w, rest % w
                        assert x[b, c, zz, ii, jj] == got[b, c, z, i, j]


def test_positive_negative_pair():
    score = np.array([[0.9], [0.2], [0.8], [0.4], [0.5]], "float32")
    label = np.array([[1.0], [0.0], [1.0], [0.0], [1.0]], "float32")
    query = np.array([[1], [1], [1], [2], [2]], "int64")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        block = main.global_block()
        for name, arr in (("Score", score), ("Label", label),
                          ("QueryID", query)):
            block.create_var(name=name, shape=arr.shape,
                             dtype=str(arr.dtype), is_data=True)
        for name in ("PositivePair", "NegativePair", "NeutralPair"):
            block.create_var(name=name)
        block.append_op("positive_negative_pair",
                        {"Score": ["Score"], "Label": ["Label"],
                         "QueryID": ["QueryID"]},
                        {"PositivePair": ["PositivePair"],
                         "NegativePair": ["NegativePair"],
                         "NeutralPair": ["NeutralPair"]},
                        {"column": -1})
    exe = fluid.Executor(fluid.CPUPlace())
    pos, neg, neu = exe.run(
        main, feed={"Score": score, "Label": label, "QueryID": query},
        fetch_list=["PositivePair", "NegativePair", "NeutralPair"])
    # query 1: pairs (0,1): 0.9>0.2 & 1>0 -> pos; (1,2): 0.2<0.8 & 0<1 -> pos
    # query 2: (3,4): 0.4<0.5 & 0<1 -> pos
    assert float(pos[0]) == 3.0
    assert float(neg[0]) == 0.0
    assert float(neu[0]) == 0.0


def test_average_accumulates_window_rollover():
    dim = 4
    param = np.full(dim, 2.0, "float32")

    def run_step(state):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            block = main.global_block()
            names = ["param", "in_sum_1", "in_sum_2", "in_sum_3",
                     "in_num_updates", "in_num_accumulates",
                     "in_old_num_accumulates"]
            feeds = {"param": param, "in_sum_1": state["s1"],
                     "in_sum_2": state["s2"], "in_sum_3": state["s3"],
                     "in_num_updates": state["nu"],
                     "in_num_accumulates": state["na"],
                     "in_old_num_accumulates": state["ona"]}
            for n in names:
                block.create_var(name=n, shape=feeds[n].shape,
                                 dtype=str(feeds[n].dtype), is_data=True)
            outs = ["out_sum_1", "out_sum_2", "out_sum_3",
                    "out_num_updates", "out_num_accumulates",
                    "out_old_num_accumulates"]
            for n in outs:
                block.create_var(name=n)
            block.append_op("average_accumulates",
                            {n: [n] for n in names},
                            {n: [n] for n in outs},
                            {"average_window": 0.5,
                             "max_average_window": 3,
                             "min_average_window": 2})
        exe = fluid.Executor(fluid.CPUPlace())
        r = exe.run(main, feed=feeds, fetch_list=outs)
        return {"s1": r[0].astype("float32"),
                "s2": r[1].astype("float32"), "s3": r[2].astype("float32"),
                "nu": r[3].astype("int64"), "na": r[4].astype("int64"),
                "ona": r[5].astype("int64")}

    state = {"s1": np.zeros(dim, "float32"), "s2": np.zeros(dim, "float32"),
             "s3": np.zeros(dim, "float32"),
             "nu": np.zeros(1, "int64"), "na": np.zeros(1, "int64"),
             "ona": np.zeros(1, "int64")}
    state = run_step(state)      # num_acc=1 < min_window 2: accumulate only
    np.testing.assert_allclose(state["s1"], param)
    assert int(state["na"][0]) == 1
    state = run_step(state)      # num_acc=2 >= min(3, 2*0.5=1)->2: rollover
    # reference quirk (average_accumulates_op.h): the fold uses in_sum_1 +
    # in_sum_2 (PRE-update), so the rollover step's own param is dropped
    np.testing.assert_allclose(state["s3"], param)
    np.testing.assert_allclose(state["s1"], 0.0)
    assert int(state["na"][0]) == 0 and int(state["ona"][0]) == 2


def test_detection_map_op():
    # one image, two gt boxes of class 0/1, three detections
    dets = [np.array([[0, 0.9, 0.0, 0.0, 1.0, 1.0],
                      [0, 0.6, 5.0, 5.0, 6.0, 6.0],
                      [1, 0.8, 2.0, 2.0, 3.0, 3.0]], "float32")]
    gts = [np.array([[0, 0.0, 0.0, 1.0, 1.0],
                     [1, 2.0, 2.0, 3.0, 3.0]], "float32")]
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        block = main.global_block()
        block.create_var(name="DetectRes", lod_level=1, dtype="float32",
                         is_data=True)
        block.create_var(name="Label", lod_level=1, dtype="float32",
                         is_data=True)
        for n in ("MAP", "AccumPosCount", "AccumTruePos", "AccumFalsePos"):
            block.create_var(name=n)
        block.append_op("detection_map",
                        {"DetectRes": ["DetectRes"], "Label": ["Label"]},
                        {"MAP": ["MAP"], "AccumPosCount": ["AccumPosCount"],
                         "AccumTruePos": ["AccumTruePos"],
                         "AccumFalsePos": ["AccumFalsePos"]},
                        {"class_num": 2, "overlap_threshold": 0.5,
                         "ap_type": "integral"})
    exe = fluid.Executor(fluid.CPUPlace(), mode="eager")
    got = exe.run(main, feed={"DetectRes": [dets[0]], "Label": [gts[0]]},
                  fetch_list=["MAP"], use_program_cache=False)
    # class 0: det .9 matches (tp), det .6 misses (fp) -> AP = 1.0
    # class 1: det .8 matches -> AP = 1.0  => mAP = 1.0
    np.testing.assert_allclose(np.asarray(got[0]), [1.0], atol=1e-6)


def test_nn_wrappers_l2_normalize_multiplex_one_hot_smooth_l1():
    rng = np.random.RandomState(11)
    x = rng.normal(0, 1, (4, 6)).astype("float32")
    y = rng.normal(0, 1, (4, 6)).astype("float32")
    ids = np.array([[1], [0], [1], [0]], "int32")
    labels = np.array([[2], [0], [1], [3]], "int64")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = layers.data("x", shape=[6])
        yv = layers.data("y", shape=[6])
        iv = layers.data("ids", shape=[1], dtype="int32")
        lv = layers.data("lab", shape=[1], dtype="int64")
        norm = layers.l2_normalize(xv, axis=1)
        mux = layers.multiplex([xv, yv], iv)
        oh = layers.one_hot(lv, depth=4)
        sl1 = layers.smooth_l1(xv, yv)
    exe = fluid.Executor(fluid.CPUPlace())
    feeds = {"x": x, "y": y, "ids": ids, "lab": labels}
    n, m, o, s = exe.run(main, feed=feeds, fetch_list=[norm, mux, oh, sl1])

    np.testing.assert_allclose(
        n, x / np.sqrt((x ** 2).sum(1, keepdims=True)), rtol=1e-5)
    np.testing.assert_allclose(m, np.where(ids == 1, y, x), rtol=1e-6)
    np.testing.assert_allclose(o, np.eye(4, dtype="float32")[labels[:, 0]])
    d = x - y
    per = np.where(np.abs(d) < 1.0, 0.5 * d * d, np.abs(d) - 0.5).sum(1)
    np.testing.assert_allclose(s.reshape(-1), per, rtol=1e-5)


def test_nn_wrappers_expand_pad_crop_label_smooth():
    rng = np.random.RandomState(12)
    x = rng.rand(2, 3).astype("float32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = layers.data("x", shape=[3])
        e = layers.expand(xv, [2, 1])
        p = layers.pad(xv, [0, 0, 1, 1], pad_value=9.0)
        c = layers.crop(xv, shape=[2, 2], offsets=[0, 1])
        ls = layers.label_smooth(xv, epsilon=0.2)
    exe = fluid.Executor(fluid.CPUPlace())
    ev, pv, cv, lsv = exe.run(main, feed={"x": x},
                              fetch_list=[e, p, c, ls])
    np.testing.assert_allclose(ev, np.tile(x, (2, 1)), rtol=1e-6)
    np.testing.assert_allclose(
        pv, np.pad(x, [(0, 0), (1, 1)], constant_values=9.0), rtol=1e-6)
    np.testing.assert_allclose(cv, x[0:2, 1:3], rtol=1e-6)
    np.testing.assert_allclose(lsv, 0.8 * x + 0.2 / 3, rtol=1e-5)
