"""Generation-serving subsystem tests: the paged KV arena's admission /
recycle / copy-on-write contracts, the prefill+paged-decode program
split against a full-window reference decode, the continuous-vs-
sequential BITWISE parity pin (greedy and beam), the streaming RPC
framing (item frames, terminal frames, mid-stream RemoteError,
cancellation on abandon), the ContinuousBatcher's typed backpressure,
and the registry's model_kind manifest field driving ModelServer's
engine-class choice.
"""

import json
import os
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.distributed import rpc
from paddle_tpu.serving import (CacheExhausted, ContinuousBatcher,
                                GenClient, GenerationEngine, ModelRegistry,
                                ModelServer, NoFreeSlots, PagedKVCache,
                                ServerOverloaded)
from paddle_tpu.testing.models import export_tiny_lm

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")

VOCAB = 17


@pytest.fixture(scope="module")
def lm_bundle(tmp_path_factory):
    """One exported tiny LM shared by the module (module-scoped: the
    bundle is immutable on disk; every engine loads it into its own
    private scope)."""
    d = str(tmp_path_factory.mktemp("genlm") / "model")
    main, scope, logits = export_tiny_lm(d, vocab=VOCAB, emb=8, heads=2,
                                         n_layers=2, max_pos=64, seed=3)
    return d


def _engine(d, **kw):
    kw.setdefault("max_seqs", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("max_len", 32)
    kw.setdefault("prefill_buckets", (8,))
    return GenerationEngine(d, **kw)


def _drain(eng, handle, first, finished):
    toks = list(first)
    while not finished:
        stepped = eng.step()
        assert stepped, "engine.step() stalled with an active sequence"
        for h, ts, f in stepped:
            if h is handle:
                toks += ts
                finished = f
    return toks


# ---------------------------------------------------------------------------
# PagedKVCache: admission, recycle, copy-on-write
# ---------------------------------------------------------------------------

def test_kvcache_exhaustion_typed_and_admission_is_atomic():
    c = PagedKVCache(1, 1, 4, num_blocks=4, block_size=4)
    c.admit("a", 8)                      # 2 blocks promised
    c.admit("b", 8)                      # 2 more
    with pytest.raises(CacheExhausted):
        c.admit("c", 4)                  # nothing uncommitted left
    # the failed admit changed NOTHING: a and b still fit their budgets
    assert c.stats()["sequences"] == 2
    assert np.array_equal(c.append_slots("a", 8), np.arange(8))
    with pytest.raises(CacheExhausted):
        c.append_slots("a", 1)           # over its admitted budget
    c.release("a")
    c.admit("c", 8)                      # freed blocks re-admit

def test_kvcache_recycle_then_realloc_reuses_freed_blocks():
    c = PagedKVCache(1, 1, 4, num_blocks=8, block_size=4)
    c.admit("a", 8)
    used = {int(s) // 4 for s in c.append_slots("a", 8)}
    assert c.stats()["blocks_in_use"] == 2
    c.release("a")
    assert c.stats()["blocks_in_use"] == 0
    c.admit("b", 8)
    slots = c.append_slots("b", 8)
    # the most-recently-freed blocks come back first: b reuses a's
    assert {int(s) // 4 for s in slots} == {0, 1} == used

def test_kvcache_cow_fork_leaves_parent_blocks_bitwise_intact():
    import jax.numpy as jnp
    c = PagedKVCache(1, 2, 4, num_blocks=8, block_size=4)
    c.admit("p", 8, cow_headroom=1)
    slots = c.append_slots("p", 6)       # blocks 0 (full) + 1 (half)
    rows = np.random.RandomState(0).normal(
        0, 1, (6, 2, 4)).astype(np.float32)
    flat = c.k[0].reshape(-1, 2, 4)
    c.k[0] = flat.at[slots].set(rows).reshape(c.k[0].shape)
    before = np.asarray(c.k[0]).copy()

    c.admit("q", 8, cow_headroom=1)
    c.fork("p", "q")
    assert c.context_len("q") == 6
    # q writes its next token: the shared tail block must COW, and the
    # parent's blocks must be bit-for-bit untouched
    q_slot = c.append_slots("q", 1)[0]
    assert q_slot // 4 not in {0, 1}     # a fresh block, not p's tail
    assert c.cow_copies == 1
    c.k[0] = c.k[0].reshape(-1, 2, 4).at[q_slot].set(
        np.ones((2, 4), np.float32) * 9).reshape(c.k[0].shape)
    after = np.asarray(c.k[0])
    np.testing.assert_array_equal(before[0], after[0])
    np.testing.assert_array_equal(before[1], after[1])
    # the COW copy carried the shared prefix content into q's new block
    np.testing.assert_array_equal(
        after.reshape(-1, 2, 4)[(q_slot // 4) * 4 + 1], rows[5])

def test_kvcache_reorder_is_atomic_for_permutations():
    c = PagedKVCache(1, 1, 2, num_blocks=8, block_size=2)
    for s in ("a", "b"):
        c.admit(s, 4, cow_headroom=1)
    c.append_slots("a", 3)
    c.append_slots("b", 1)
    ta, tb = c.block_table("a", 4).copy(), c.block_table("b", 4).copy()
    c.reorder({"a": "b", "b": "a"})      # swap
    assert np.array_equal(c.block_table("a", 4), tb)
    assert np.array_equal(c.block_table("b", 4), ta)
    assert c.context_len("a") == 1 and c.context_len("b") == 3
    c.release("a")
    c.release("b")
    assert c.stats()["blocks_in_use"] == 0


# ---------------------------------------------------------------------------
# GenerationEngine: split correctness + compile-once + parity pins
# ---------------------------------------------------------------------------

def _reference_greedy(bundle_dir, prompt, max_new):
    """Full-window teacher-forced argmax decode straight through the
    ORIGINAL saved program — the unsplit ground truth."""
    exe = fluid.Executor()
    scope = fluid.Scope()
    program, feeds, fetches = fluid.io.load_inference_model(
        bundle_dir, exe, scope=scope)
    toks, out = list(prompt), []
    for _ in range(max_new):
        T = len(toks)
        feed = {"tokens": np.asarray(toks, np.int64).reshape(1, T, 1),
                "positions": np.arange(T, dtype=np.int64).reshape(1, T, 1)}
        lg = exe.run(program, feed=feed, fetch_list=fetches,
                     scope=scope)[0]
        t = int(np.argmax(lg[0, -1]))
        out.append(t)
        toks.append(t)
    return out

def test_engine_greedy_matches_full_window_reference(lm_bundle):
    eng = _engine(lm_bundle)
    compiled = eng.warmup()
    assert compiled == 2                 # one decode + one prefill bucket
    h, first, fin = eng.start([1, 2, 3], 6)
    toks = _drain(eng, h, first, fin)
    assert toks == _reference_greedy(lm_bundle, [1, 2, 3], 6)
    st = eng.stats()
    assert st["warmed"] and st["hot_recompiles"] == 0
    assert st["compiles"] == 2 and st["hits"] >= 6
    # everything retired: slots and blocks all recycled
    assert st["active_sequences"] == 0 and st["blocks_in_use"] == 0

def test_engine_admission_errors_are_typed(lm_bundle):
    eng = _engine(lm_bundle, max_seqs=2, num_blocks=4)
    eng.warmup()
    with pytest.raises(ValueError, match="max_len"):
        eng.start([1], 99)
    h, first, fin = eng.start([1, 2], 10)    # holds 3 of the 4 blocks
    assert not fin
    with pytest.raises(CacheExhausted):
        eng.start([3], 10)               # needs 3, only 1 uncommitted
    h2, _, fin2 = eng.start([3], 2)      # 1 block: fits; slots now full
    with pytest.raises(NoFreeSlots):
        eng.start([4], 2)
    eng.abort(h)
    if not fin2:
        eng.abort(h2)
    assert eng.stats()["active_sequences"] == 0
    eng.start([3], 4)                    # capacity recycled

def _run_engine_requests(eng, requests, sequential):
    """Drive requests through the engine one-at-a-time (sequential) or
    all-in-flight (continuous); returns each request's token stream."""
    if sequential:
        return [_drain(eng, *eng.start(p, m, s)) for p, m, s in requests]
    streams = [[] for _ in requests]
    live = {}
    for i, (p, m, s) in enumerate(requests):
        h, first, fin = eng.start(p, m, s)
        streams[i] += first
        if not fin:
            live[id(h)] = i
    while live:
        for h, ts, f in eng.step():
            i = live.get(id(h))
            if i is None:
                continue
            streams[i] += ts
            if f:
                del live[id(h)]
    return streams

def test_parity_continuous_vs_sequential_greedy_topk_beam(lm_bundle):
    """THE acceptance pin: joining a running ragged batch changes no
    sequence's tokens — greedy, seeded top-k and beam all produce
    bitwise-identical streams whether decoded alone or continuously
    batched, with zero hot-path recompiles either way."""
    requests = [
        ([1, 2], 5, None),
        ([5], 7, {"mode": "topk", "top_k": 4, "seed": 11}),
        ([7, 8, 9, 10], 4, {"mode": "beam", "beam_size": 2,
                            "eos_id": 0}),
        ([2, 4, 6], 6, {"mode": "topk", "top_k": 3, "seed": 5,
                        "temperature": 0.7}),
    ]
    eng = _engine(lm_bundle, max_seqs=5)
    eng.warmup()
    seq_streams = _run_engine_requests(eng, requests, sequential=True)
    cont_streams = _run_engine_requests(eng, requests, sequential=False)
    assert seq_streams == cont_streams
    st = eng.stats()
    assert st["hot_recompiles"] == 0
    assert st["active_sequences"] == 0 and st["blocks_in_use"] == 0
    # same engine, same seeds, fresh run: topk reproduces exactly
    again = _run_engine_requests(eng, requests, sequential=False)
    assert again == cont_streams

def test_beam_decode_emits_best_hypothesis_once(lm_bundle):
    eng = _engine(lm_bundle)
    eng.warmup()
    h, first, fin = eng.start([1, 2, 3], 5,
                              {"mode": "beam", "beam_size": 3})
    assert first == [] and not fin       # beams emit only on completion
    toks = _drain(eng, h, first, fin)
    assert len(toks) == 5
    assert all(0 <= t < VOCAB for t in toks)
    assert eng.stats()["active_sequences"] == 0
    assert eng.stats()["blocks_in_use"] == 0


# ---------------------------------------------------------------------------
# ContinuousBatcher: step-boundary admission + typed backpressure
# ---------------------------------------------------------------------------

def test_batcher_queues_past_capacity_and_completes_fifo(lm_bundle):
    eng = _engine(lm_bundle, max_seqs=2)
    eng.warmup()
    b = ContinuousBatcher(eng, capacity=8)
    try:
        streams = [b.submit([1 + i, 2], 4 + i % 3) for i in range(6)]
        outs = [list(s) for s in streams]
        for i, o in enumerate(outs):
            assert len(o) == 4 + i % 3, (i, o)
        st = b.stats()
        assert st["requests"] == 6 and st["rejected"] == 0
        assert st["in_flight"] == 0 and st["queue_depth"] == 0
    finally:
        assert b.close()
    assert eng.stats()["hot_recompiles"] == 0

def test_batcher_overload_rejects_fast_typed(lm_bundle):
    eng = _engine(lm_bundle, max_seqs=1)
    eng.warmup()
    b = ContinuousBatcher(eng, capacity=1)
    try:
        s1 = b.submit([1, 2], 20)        # occupies the only slot
        deadline = time.monotonic() + 10
        while b.stats()["in_flight"] == 0:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        b.submit([3], 4)                 # fills the wait queue
        with pytest.raises(ServerOverloaded):
            b.submit([4], 4)
        assert b.stats()["rejected"] == 1
        assert len(list(s1)) == 20
    finally:
        b.close()

def test_batcher_cancel_frees_capacity(lm_bundle):
    eng = _engine(lm_bundle, max_seqs=1)
    eng.warmup()
    b = ContinuousBatcher(eng, capacity=4)
    try:
        s1 = b.submit([1, 2], 25)
        it = iter(s1)
        next(it)                         # stream is live
        s2 = b.submit([3], 3)            # queued behind it
        s1.close()                       # cancel mid-generation
        assert len(list(s2)) == 3        # the queued request got the slot
        assert eng.stats()["active_sequences"] == 0
    finally:
        b.close()

def test_never_satisfiable_requests_raise_valueerror_not_capacity(lm_bundle):
    """A request that can NEVER be admitted (beam wider than the slot
    count, worst case bigger than the whole arena) must be a typed
    bad-request, not a transient capacity error the strict-FIFO
    scheduler would wait on forever with the queue wedged behind it."""
    eng = _engine(lm_bundle, max_seqs=2, num_blocks=4)
    eng.warmup()
    with pytest.raises(ValueError, match="decode slots"):
        eng.start([1], 4, {"mode": "beam", "beam_size": 3})
    with pytest.raises(ValueError, match="never be admitted"):
        eng.start([1, 2], 28)            # needs 8 blocks, arena has 4
    # through the batcher: the bad request fails ITS stream and the
    # queue keeps serving everyone behind it
    b = ContinuousBatcher(eng)
    try:
        bad = b.submit([1], 4, {"mode": "beam", "beam_size": 3})
        good = b.submit([2, 3], 4)
        with pytest.raises(ValueError, match="decode slots"):
            list(bad)
        assert len(list(good)) == 4
    finally:
        b.close()


def test_batcher_rejects_malformed_sampling_without_queueing(lm_bundle):
    eng = _engine(lm_bundle)
    eng.warmup()
    b = ContinuousBatcher(eng)
    try:
        with pytest.raises(ValueError, match="mode"):
            b.submit([1], 4, {"mode": "nucleus"})
        assert b.stats()["queue_depth"] == 0
    finally:
        b.close()


# ---------------------------------------------------------------------------
# streaming RPC framing (transport-level, no model)
# ---------------------------------------------------------------------------

class _StreamHandler:
    def __init__(self):
        self.closed_early = False

    def count(self, n, fail_at=None, width=4, exc=RuntimeError):
        def gen():
            try:
                for i in range(int(n)):
                    if fail_at is not None and i == fail_at:
                        raise exc(f"boom at {i}")
                    yield {"i": i, "arr": np.full((width,), i, np.float32)}
            except GeneratorExit:
                self.closed_early = True
                raise
        return gen()

    def unary(self, x):
        return x + 1

def test_rpc_streaming_frames_and_midstream_error():
    h = _StreamHandler()
    server = rpc.RpcServer(h)
    server.serve_in_thread()
    try:
        c = rpc.RpcClient(server.address)
        items = list(c.stream("count", n=4))
        assert [it["i"] for it in items] == [0, 1, 2, 3]
        np.testing.assert_array_equal(items[2]["arr"],
                                      np.full((4,), 2, np.float32))
        # the SAME connection serves unary calls after a clean stream
        assert c.call("unary", x=4) == 5
        # mid-stream handler failure: items up to it arrive, then the
        # structured RemoteError (code preserved)
        got = []
        with pytest.raises(rpc.RemoteError) as ei:
            for it in c.stream("count", n=4, fail_at=2):
                got.append(it["i"])
        assert got == [0, 1] and ei.value.code == "RuntimeError"
        assert "boom at 2" in ei.value.remote_message
        # an OSError raised by the HANDLER's own code is a remote
        # failure owed its error frame — not "client vanished" (which
        # only a send failure is) — so it crosses structured too
        with pytest.raises(rpc.RemoteError) as ei:
            list(c.stream("count", n=4, fail_at=1, exc=OSError))
        assert ei.value.code == "OSError"
        # ... and the connection still serves afterwards
        assert c.call("unary", x=1) == 2
        c.close()
    finally:
        server.kill()

def test_rpc_stream_abandon_cancels_the_handler_generator():
    h = _StreamHandler()
    server = rpc.RpcServer(h)
    server.serve_in_thread()
    try:
        c = rpc.RpcClient(server.address)
        # enough frames/bytes that the server cannot outrun the socket
        # buffers: it must still be streaming when the client abandons
        s = c.stream("count", n=1_000_000, width=512)
        assert next(s)["i"] == 0
        s.close()                        # abandon mid-stream
        deadline = time.monotonic() + 10
        while not h.closed_early:
            assert time.monotonic() < deadline, \
                "server generator was never closed"
            time.sleep(0.01)
        # the abandoned stream dropped the conn; the client reconnects
        assert c.call("unary", x=0) == 1
        c.close()
    finally:
        server.kill()

def test_rpc_unary_call_on_streaming_method_raises_typed():
    server = rpc.RpcServer(_StreamHandler())
    server.serve_in_thread()
    try:
        c = rpc.RpcClient(server.address)
        with pytest.raises(RuntimeError, match="stream"):
            c.call("count", n=3)
        # stream() on a unary method degrades to a one-item stream
        assert list(c.stream("unary", x=1)) == [2]
        c.close()
    finally:
        server.kill()


# ---------------------------------------------------------------------------
# ModelServer + GenClient end to end, registry model_kind
# ---------------------------------------------------------------------------

def _gen_server(model_dir, **kw):
    kw.setdefault("model_kind", "generative")
    kw.setdefault("gen_opts", dict(max_seqs=4, block_size=4, num_blocks=64,
                                   max_len=32, prefill_buckets=(8,)))
    server = ModelServer(model_dir, **kw)
    server.start()
    return server

def test_generate_streams_over_the_wire(lm_bundle):
    server = _gen_server(lm_bundle)
    try:
        with GenClient(server.address) as c:
            toks = list(c.generate([1, 2, 3], 6))
            assert toks == _reference_greedy(lm_bundle, [1, 2, 3], 6)
            beam = list(c.generate([1, 2, 3], 4,
                                   {"mode": "beam", "beam_size": 2}))
            assert len(beam) == 4
            health = c.health()
            assert health["model_kind"] == "generative" and health["warmed"]
            st = c.stats()
            assert st["engine"]["hot_recompiles"] == 0
            assert st["engine"]["active_sequences"] == 0
            assert st["batcher"]["tokens_emitted"] >= 10
            # the feed-forward surface is closed off, typed
            with pytest.raises(rpc.RemoteError, match="GENERATIVE"):
                c._rpc.call("infer", feed={"x": np.zeros((1, 2))})
    finally:
        assert server.shutdown()

def test_generate_overload_is_typed_across_the_wire(lm_bundle):
    server = _gen_server(
        lm_bundle, queue_capacity=1,
        gen_opts=dict(max_seqs=1, block_size=4, num_blocks=64, max_len=32,
                      prefill_buckets=(8,)))
    try:
        import threading
        c1, c2, c3 = (GenClient(server.address) for _ in range(3))
        try:
            g1 = c1.generate([1, 2], 25)
            next(g1)                     # slot taken
            g2_out = []
            t2 = threading.Thread(
                target=lambda: g2_out.extend(c2.generate([3], 3)))
            t2.start()                   # queued behind g1 (capacity 1)
            deadline = time.monotonic() + 10
            while server.batcher.stats()["queue_depth"] == 0:
                assert time.monotonic() < deadline
                time.sleep(0.01)
            with pytest.raises(ServerOverloaded):
                list(c3.generate([4], 3))
            assert len(list(g1)) == 24   # 25 minus the one consumed
            t2.join(30)
            assert g2_out and len(g2_out) == 3
        finally:
            for c in (c1, c2, c3):
                c.close()
    finally:
        server.shutdown()

def test_generative_server_rejects_batching_false(lm_bundle):
    with pytest.raises(ValueError, match="batching=False"):
        ModelServer(lm_bundle, model_kind="generative", batching=False,
                    gen_opts=dict(max_seqs=2, block_size=4, num_blocks=64,
                                  max_len=32, prefill_buckets=(8,)))


def test_registry_model_kind_field_and_server_engine_pick(lm_bundle,
                                                          tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    with pytest.raises(ValueError, match="model_kind"):
        reg.publish("lm", lm_bundle, model_kind="diffusion")
    v = reg.publish("lm", lm_bundle, model_kind="generative")
    assert reg.model_kind("lm", v) == "generative"
    assert reg.manifest("lm", v)["model_kind"] == "generative"
    path, _ = reg.resolve("lm", v)

    # ModelServer picks the engine class from the manifest alone
    server = ModelServer(path, gen_opts=dict(
        max_seqs=2, block_size=4, num_blocks=64, max_len=32,
        prefill_buckets=(8,)))
    try:
        assert server.model_kind == "generative"
        assert isinstance(server.engine, GenerationEngine)
        server.start()
        with GenClient(server.address) as c:
            assert len(list(c.generate([1, 2], 3))) == 3
    finally:
        server.shutdown()

    # a pre-upgrade manifest (no model_kind field) defaults feedforward
    mpath = os.path.join(path, "VERSION.json")
    with open(mpath) as f:
        manifest = json.load(f)
    manifest.pop("model_kind")
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    assert reg.model_kind("lm", v) == "feedforward"
    from paddle_tpu.serving.server import sniff_model_kind
    assert sniff_model_kind(path) == "feedforward"
    assert sniff_model_kind(str(tmp_path)) == "feedforward"  # no manifest
