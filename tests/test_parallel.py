"""Multi-device sharding tests on the 8-virtual-CPU-device mesh (conftest).

Reference strategy: the book models run with parallel=True across devices and
must match the single-device result (/root/reference/python/paddle/fluid/
tests/book/test_recognize_digits.py:77-86; parallel_do semantics
operators/parallel_do_op.cc:39-69). Here the parallel_do equivalent is GSPMD:
`shard_program_step` pjit-compiles the same program over a Mesh, so dp / dp×tp
/ sharded-optimizer-state cases must agree numerically with the plain
single-device Executor on identical feeds and init.
"""

import numpy as np
import jax
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import testing as models
from paddle_tpu.parallel import (make_mesh, ShardingPlan, shard_program_step,
                                 place_feed)
from jax.sharding import PartitionSpec as P


def _build_mlp(batch, opt="momentum"):
    return models.build_mlp(opt=opt)


def _build_convnet(batch):
    """Tiny ResNet-style slice: conv+BN(NHWC)+residual add+pool+fc+momentum —
    the flagship benchmark's op mix at dryrun shapes."""
    return models.build_convnet_slice()


# n steps over ONE fixed batch: keeps the loss sequence monotone so the
# 'actually trains' assertions hold, while still exercising n update steps.
def _mlp_feeds(n=3):
    return [models.mlp_feed(16)] * n


def _conv_feeds(n=3):
    return [models.convnet_feed(16)] * n


def _single_device_losses(build, feeds, **bkw):
    main, startup, loss = build(**bkw)
    scope = fluid.Scope()
    exe = fluid.Executor(mode="jit")
    exe.run(startup, scope=scope)
    out = []
    for f in feeds:
        out.append(float(exe.run(main, feed=f, fetch_list=[loss],
                                 scope=scope)[0]))
    return out


def _sharded_losses(build, feeds, plan_kw, mesh_axes, bkw, donate=False):
    main, startup, loss = build(**bkw)
    scope = fluid.Scope()
    exe = fluid.Executor(mode="jit")
    exe.run(startup, scope=scope)
    mesh = make_mesh(8, axes=mesh_axes)
    plan = ShardingPlan(mesh, **plan_kw)
    fn, state, _ = shard_program_step(exe, main, feeds[0], [loss], plan,
                                      scope=scope, donate=donate)
    out = []
    block = main.global_block()
    with mesh:
        for f in feeds:
            fd = exe._prepare_feed(block, dict(f))
            fd = {n: place_feed(v, plan, n) for n, v in fd.items()}
            state, fetches = fn(state, fd)
            out.append(float(np.asarray(fetches[0])))
    return out


def test_dp_matches_single_device():
    feeds = _mlp_feeds()
    ref = _single_device_losses(_build_mlp, feeds, batch=16)
    got = _sharded_losses(_build_mlp, feeds, {}, ("dp",), dict(batch=16))
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-6)
    assert got[-1] < got[0]  # actually trains


def test_dp_tp_matches_single_device():
    feeds = _mlp_feeds()
    ref = _single_device_losses(_build_mlp, feeds, batch=16)
    got = _sharded_losses(_build_mlp, feeds, {}, ("dp", "tp"), dict(batch=16))
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-6)


def test_sharded_optimizer_state_matches():
    """ZeRO-1 analog: accumulators sharded over dp must not change numerics."""
    feeds = _mlp_feeds()
    ref = _single_device_losses(_build_mlp, feeds, batch=16, opt="adam")
    got = _sharded_losses(_build_mlp, feeds, {"shard_opt_state": True},
                          ("dp",), dict(batch=16, opt="adam"))
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-6)


def test_dp_convnet_bn_matches_single_device():
    """conv+BN under dp: BN statistics are global-batch (the jit computation
    is one logical program; GSPMD inserts the cross-replica reductions), so
    sharded must equal single-device exactly up to float assoc error."""
    feeds = _conv_feeds()
    ref = _single_device_losses(_build_convnet, feeds, batch=16)
    got = _sharded_losses(_build_convnet, feeds, {}, ("dp",), dict(batch=16))
    np.testing.assert_allclose(got, ref, rtol=5e-5, atol=5e-6)
    assert got[-1] < got[0]


def _build_seq_model(batch):
    return models.build_seq_slice()


def test_dp_lod_seq_matches_single_device():
    """Ragged (LoD) feeds shard their padded batch dim across dp; numerics
    must match the single-device run (reference SplitLoDTensor semantics)."""
    feeds = [models.seq_feed(16, seed=3)] * 3
    ref = _single_device_losses(_build_seq_model, feeds, batch=16)
    got = _sharded_losses(_build_seq_model, feeds, {}, ("dp",), dict(batch=16))
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-6)
    assert got[-1] < got[0]


def test_conv_filter_never_spatially_sharded():
    mesh = make_mesh(8, axes=("dp", "tp"))
    plan = ShardingPlan(mesh)
    # OIHW conv filter: last dims are spatial; must stay replicated by default
    assert plan.spec_for_param("conv2d_0.w_0", (64, 3, 8, 8)) == P()
    # fc weight: TP on the output dim
    assert plan.spec_for_param("fc_0.w_0", (128, 64)) == P(None, "tp")
    # with shard_conv_filters, output-channel dim only
    plan2 = ShardingPlan(mesh, shard_conv_filters=True)
    assert plan2.spec_for_param("conv2d_0.w_0", (64, 3, 8, 8)) == P("tp")


def test_opt_state_spec():
    mesh = make_mesh(8, axes=("dp",))
    plan = ShardingPlan(mesh, shard_opt_state=True)
    # velocity of a replicated conv filter shards dim 0 over dp
    assert plan.spec_for_param("conv2d_0.w_0_velocity_0", (64, 3, 3, 3)) == \
        P("dp", None, None, None)
    # the param itself stays replicated
    assert plan.spec_for_param("conv2d_0.w_0", (64, 3, 3, 3)) == P()
    # tiny accumulators (beta powers) stay replicated
    assert plan.spec_for_param("fc_0.w_0_beta1_pow_0", (1,)) == P()


def test_zero1_shards_arbitrary_accumulator_names():
    """ZeRO-1 accumulator detection comes from the optimizer registry tag,
    not name patterns: an optimizer with a novel accumulator name still gets
    its state sharded over dp (VERDICT round-3 weak #5)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid.optimizer import SGD
    from paddle_tpu.parallel import make_mesh, ShardingPlan
    from jax.sharding import PartitionSpec as P

    class WeirdSGD(SGD):
        def _append_optimize_op(self, block, param_and_grad, startup):
            p, g = param_and_grad
            acc = self._add_accumulator("exotic_running_stat", p, startup)
            block.append_op(
                "sgd", inputs={"Param": [p.name], "Grad": [g.name],
                               "LearningRate": [self._lr_var.name]},
                outputs={"ParamOut": [p.name]})

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[16])
        y = fluid.layers.fc(x, size=8)
        loss = fluid.layers.mean(y)
        WeirdSGD(learning_rate=0.1).minimize(loss, startup)

    block = main.global_block()
    acc_vars = [v for v in block.vars.values()
                if getattr(v, "optimizer_accumulator_for", None)]
    assert acc_vars, "registry tag missing on accumulator vars"
    assert any("exotic_running_stat" in v.name for v in acc_vars)

    mesh = make_mesh(8, axes=("dp",))
    plan = ShardingPlan(mesh, shard_opt_state=True)
    v = acc_vars[0]
    spec = plan.spec_for_param(v.name, v.shape, var=v)
    assert spec == P("dp", None), spec
    # without the tag (deserialized program), the regex fallback does NOT
    # recognize the exotic name -> replicated (the old silent behavior, now
    # only a fallback)
    assert plan.spec_for_param(v.name, v.shape) == P()


def test_sharding_plan_dict_round_trip():
    """to_dict()/from_dict() pin: a plan — mesh, axis roles, custom
    rules, policy switches — survives JSON round-trip and rebuilds over
    this process's devices; schema violations are typed ValueErrors (the
    placement planner persists plans through this surface)."""
    import json

    mesh = make_mesh(8, axes=("dp", "tp"))
    plan = ShardingPlan(mesh, rules=[(r"^emb_", P(None, "tp")),
                                     (r"_stat$", P(("dp", "tp")))],
                        shard_conv_filters=True, shard_opt_state=True)
    doc = json.loads(json.dumps(plan.to_dict()))
    assert doc["schema"] == "pdtpu-sharding-plan-v1"
    rt = ShardingPlan.from_dict(doc)
    assert rt.to_dict() == plan.to_dict()
    assert rt.mesh.axis_names == mesh.axis_names
    assert rt.mesh.devices.shape == mesh.devices.shape
    # the rebuilt plan assigns identical specs
    for name, shape in (("fc_0.w_0", (16, 32)), ("emb_table", (12, 8)),
                        ("x_stat", (4,)), ("fc_0.w_0_velocity", (16, 32))):
        assert rt.spec_for_param(name, shape) == \
            plan.spec_for_param(name, shape), name
    for bad in ({}, {"schema": "pdtpu-sharding-plan-v1"},
                {"schema": "pdtpu-sharding-plan-v1",
                 "mesh": {"axes": ["dp"], "shape": [4, 2]}},
                {"schema": "pdtpu-sharding-plan-v1",
                 "mesh": {"axes": ["dp"], "shape": [8]},
                 "rules": [["ok", [["dp"], 3]]]}):
        with pytest.raises(ValueError):
            ShardingPlan.from_dict(bad)
