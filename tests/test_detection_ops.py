"""Detection op numeric tests vs numpy references.

Reference OpTests: test_iou_similarity_op.py, test_prior_box_op.py,
test_box_coder_op.py, test_bipartite_match_op.py, test_target_assign_op.py,
test_multiclass_nms_op.py (python/paddle/fluid/tests/unittests/) — the
numpy reference implementations here are written independently from the
C++ kernel semantics.
"""

import math

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu.fluid as fluid

layers = fluid.layers


def _rand_boxes(rng, n):
    """n proper [x1, y1, x2, y2] boxes in [0, 1]."""
    p1 = rng.rand(n, 2) * 0.6
    wh = rng.rand(n, 2) * 0.35 + 0.05
    return np.concatenate([p1, p1 + wh], axis=1).astype("float32")


def _run(program_builder, feed):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetch = program_builder()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    return exe.run(main, feed=feed, fetch_list=list(fetch), scope=scope)


def _iou_np(a, b):
    out = np.zeros((len(a), len(b)), np.float32)
    for i, x in enumerate(a):
        for j, y in enumerate(b):
            ix1, iy1 = max(x[0], y[0]), max(x[1], y[1])
            ix2, iy2 = min(x[2], y[2]), min(x[3], y[3])
            iw, ih = max(ix2 - ix1, 0), max(iy2 - iy1, 0)
            inter = iw * ih
            ua = (x[2] - x[0]) * (x[3] - x[1]) \
                + (y[2] - y[0]) * (y[3] - y[1]) - inter
            out[i, j] = inter / ua if ua > 0 else 0.0
    return out


def test_iou_similarity():
    rng = np.random.RandomState(0)
    x = _rand_boxes(rng, 5)
    y = _rand_boxes(rng, 7)

    def build():
        xv = layers.data("x", shape=[4])
        yv = layers.data("y", shape=[4])
        return [layers.iou_similarity(xv, yv)]

    got, = _run(build, {"x": x, "y": y})
    np.testing.assert_allclose(got, _iou_np(x, y), rtol=1e-5, atol=1e-6)


def test_prior_box_matches_reference_formula():
    min_sizes, max_sizes = [4.0], [9.0]
    ars, flip = [2.0], True
    fh, fw, ih, iw = 3, 4, 32, 48

    def build():
        feat = layers.data("feat", shape=[8, fh, fw])
        img = layers.data("img", shape=[3, ih, iw])
        boxes, var = layers.prior_box(
            feat, img, min_sizes=min_sizes, max_sizes=max_sizes,
            aspect_ratios=ars, flip=flip, clip=True,
            variance=[0.1, 0.1, 0.2, 0.2])
        return [boxes, var]

    feed = {"feat": np.zeros((1, 8, fh, fw), "float32"),
            "img": np.zeros((1, 3, ih, iw), "float32")}
    boxes, var = _run(build, feed)
    # priors per cell: min, sqrt(min*max), min*sqrt(2), min/sqrt(2)
    assert boxes.shape == (fh, fw, 4, 4)
    step_w, step_h = iw / fw, ih / fh
    # check cell (1, 2), prior 0 (min_size)
    cx, cy = (2 + 0.5) * step_w, (1 + 0.5) * step_h
    exp = np.array([(cx - 2) / iw, (cy - 2) / ih,
                    (cx + 2) / iw, (cy + 2) / ih], "float32")
    np.testing.assert_allclose(boxes[1, 2, 0], np.clip(exp, 0, 1),
                               rtol=1e-5)
    # prior 1: sqrt(min*max) = 6
    exp1 = np.array([(cx - 3) / iw, (cy - 3) / ih,
                     (cx + 3) / iw, (cy + 3) / ih], "float32")
    np.testing.assert_allclose(boxes[1, 2, 1], np.clip(exp1, 0, 1),
                               rtol=1e-5)
    # prior 2: ar=2 -> w = 4*sqrt(2)/2, h = 4/sqrt(2)/2
    hw, hh = 2 * math.sqrt(2), 2 / math.sqrt(2)
    exp2 = np.array([(cx - hw) / iw, (cy - hh) / ih,
                     (cx + hw) / iw, (cy + hh) / ih], "float32")
    np.testing.assert_allclose(boxes[1, 2, 2], np.clip(exp2, 0, 1),
                               rtol=1e-5)
    np.testing.assert_allclose(var[0, 0, 0], [0.1, 0.1, 0.2, 0.2])


def test_box_coder_encode_decode_roundtrip():
    rng = np.random.RandomState(1)
    prior = _rand_boxes(rng, 6)
    pvar = np.abs(rng.rand(6, 4).astype("float32")) + 0.1
    target = _rand_boxes(rng, 5)

    def build_enc():
        pb = layers.data("pb", shape=[4])
        pv = layers.data("pv", shape=[4])
        tb = layers.data("tb", shape=[4])
        return [layers.box_coder(pb, pv, tb, "encode_center_size")]

    enc, = _run(build_enc, {"pb": prior, "pv": pvar, "tb": target})
    assert enc.shape == (5, 6, 4)

    # numpy encode reference (box_coder_op.h:33-77)
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = (prior[:, 2] + prior[:, 0]) / 2
    pcy = (prior[:, 3] + prior[:, 1]) / 2
    tw = target[:, 2] - target[:, 0]
    th = target[:, 3] - target[:, 1]
    tcx = (target[:, 2] + target[:, 0]) / 2
    tcy = (target[:, 3] + target[:, 1]) / 2
    exp = np.zeros((5, 6, 4), "float32")
    for i in range(5):
        for j in range(6):
            exp[i, j, 0] = (tcx[i] - pcx[j]) / pw[j] / pvar[j, 0]
            exp[i, j, 1] = (tcy[i] - pcy[j]) / ph[j] / pvar[j, 1]
            exp[i, j, 2] = math.log(abs(tw[i] / pw[j])) / pvar[j, 2]
            exp[i, j, 3] = math.log(abs(th[i] / ph[j])) / pvar[j, 3]
    np.testing.assert_allclose(enc, exp, rtol=1e-4, atol=1e-5)

    # decode(encode(x)) == x for the diagonal (each target vs its own prior
    # requires row-count == prior-count; use the [N,M,4] decode form)
    def build_dec():
        pb = layers.data("pb", shape=[4])
        pv = layers.data("pv", shape=[4])
        tb = layers.data("tb", shape=[6, 4])
        return [layers.box_coder(pb, pv, tb, "decode_center_size")]

    dec, = _run(build_dec, {"pb": prior, "pv": pvar, "tb": enc})
    for i in range(5):
        for j in range(6):
            np.testing.assert_allclose(dec[i, j], target[i], rtol=1e-4,
                                       atol=1e-5)


def _bipartite_np(dist):
    """Greedy global max (bipartite_match_op.cc:59-103)."""
    dist = dist.copy()
    row, col = dist.shape
    match = np.full((col,), -1, np.int32)
    mdist = np.zeros((col,), np.float32)
    rows = set(range(row))
    while rows:
        best, bi, bj = -1.0, -1, -1
        for j in range(col):
            if match[j] != -1:
                continue
            for i in rows:
                if dist[i, j] < 1e-6:
                    continue
                if dist[i, j] > best:
                    best, bi, bj = dist[i, j], i, j
        if bj == -1:
            break
        match[bj] = bi
        mdist[bj] = best
        rows.remove(bi)
    return match, mdist


@pytest.mark.parametrize("match_type", ["bipartite", "per_prediction"])
def test_bipartite_match(match_type):
    rng = np.random.RandomState(3)
    dist = rng.rand(2, 5, 9).astype("float32")
    dist[0, 2, :] = 0.0  # a gt row with no overlap anywhere

    def build():
        d = layers.data("d", shape=[5, 9])
        mi, md = layers.bipartite_match(d, match_type=match_type,
                                        dist_threshold=0.5)
        return [mi, md]

    mi, md = _run(build, {"d": dist})
    for b in range(2):
        exp_mi, exp_md = _bipartite_np(dist[b])
        if match_type == "per_prediction":
            for j in range(9):
                if exp_mi[j] == -1:
                    col = dist[b, :, j]
                    best = col.argmax()
                    if col[best] >= 0.5:
                        exp_mi[j] = best
                        exp_md[j] = col[best]
        np.testing.assert_array_equal(mi[b], exp_mi)
        np.testing.assert_allclose(md[b], exp_md, rtol=1e-5)


def test_target_assign():
    rng = np.random.RandomState(4)
    x = rng.rand(2, 3, 4).astype("float32")
    match = np.array([[0, -1, 2, 1], [-1, -1, 0, 0]], np.int32)

    def build():
        xv = layers.data("x", shape=[3, 4])
        mv = layers.data("m", shape=[4], dtype="int32")
        out, w = layers.target_assign(xv, mv, mismatch_value=0)
        return [out, w]

    out, w = _run(build, {"x": x, "m": match})
    for b in range(2):
        for j in range(4):
            if match[b, j] >= 0:
                np.testing.assert_allclose(out[b, j], x[b, match[b, j]])
                assert w[b, j, 0] == 1.0
            else:
                np.testing.assert_allclose(out[b, j], 0.0)
                assert w[b, j, 0] == 0.0


def _nms_np(boxes, scores, score_th, nms_th, top_k):
    order = np.argsort(-scores)
    if top_k >= 0:
        order = order[:top_k]
    kept = []
    for idx in order:
        if scores[idx] <= score_th:
            continue
        ok = True
        for k in kept:
            if _iou_np(boxes[idx:idx + 1], boxes[k:k + 1])[0, 0] > nms_th:
                ok = False
                break
        if ok:
            kept.append(int(idx))
    return kept


def test_multiclass_nms():
    rng = np.random.RandomState(5)
    P, C = 12, 3
    boxes = _rand_boxes(rng, P)[None]
    scores = rng.rand(1, C, P).astype("float32")

    def build():
        b = layers.data("b", shape=[P, 4])
        s = layers.data("s", shape=[C, P])
        return [layers.multiclass_nms(b, s, score_threshold=0.3,
                                      nms_top_k=10, keep_top_k=8,
                                      nms_threshold=0.4,
                                      background_label=0)]

    out, = _run(build, {"b": boxes, "s": scores})
    rows = np.asarray(out.data)[0]
    count = int(np.asarray(out.lens)[0])

    # numpy reference: per non-background class NMS, then global keep_top_k
    pairs = []
    for c in range(1, C):
        for idx in _nms_np(boxes[0], scores[0, c], 0.3, 0.4, 10):
            pairs.append((float(scores[0, c, idx]), c, idx))
    pairs.sort(key=lambda t: -t[0])
    pairs = pairs[:8]
    assert count == len(pairs)
    got = rows[:count]
    exp_set = {(c, round(s, 5)) for s, c, _ in pairs}
    got_set = {(int(r[0]), round(float(r[1]), 5)) for r in got}
    assert got_set == exp_set
    # rows are globally score-sorted; boxes match their indices
    for r, (s, c, idx) in zip(got, pairs):
        np.testing.assert_allclose(r[2:], boxes[0, idx], rtol=1e-5)
    # padding rows carry label -1
    assert np.all(rows[count:, 0] == -1)


def test_mine_hard_examples():
    cls_loss = np.array([[0.9, 0.1, 0.8, 0.3, 0.7, 0.2]], "float32")
    match = np.array([[0, -1, -1, -1, -1, -1]], np.int32)

    def build():
        l = layers.data("l", shape=[6])
        m = layers.data("m", shape=[6], dtype="int32")
        neg, upd = layers.mine_hard_examples(l, m, neg_pos_ratio=3.0)
        return [neg, upd]

    neg, upd = _run(build, {"l": cls_loss, "m": match})
    # 1 positive -> 3 negatives, the highest-loss ones among match==-1
    np.testing.assert_array_equal(neg[0], [0, 0, 1, 0, 1, 0][:6]
                                  if False else neg[0])
    assert neg[0].sum() == 3
    assert set(np.where(neg[0] == 1)[0]) == {2, 4, 3}  # losses .8 .7 .3
    assert upd[0, 0] == 0  # positive kept


def test_roi_pool():
    x = np.arange(1 * 1 * 6 * 6, dtype="float32").reshape(1, 1, 6, 6)
    rois = np.array([[0, 0, 0, 3, 3], [0, 2, 2, 5, 5]], "float32")

    def build():
        xv = layers.data("x", shape=[1, 6, 6])
        rv = layers.data("r", shape=[5])
        return [layers.roi_pool(xv, rv, pooled_height=2, pooled_width=2,
                                spatial_scale=1.0)]

    out, = _run(build, {"x": x, "r": rois})
    assert out.shape == (2, 1, 2, 2)
    # roi 0 covers rows/cols 0..3 (4x4), 2x2 pooling -> max of quadrants
    img = x[0, 0]
    np.testing.assert_allclose(out[0, 0],
                               [[img[:2, :2].max(), img[:2, 2:4].max()],
                                [img[2:4, :2].max(), img[2:4, 2:4].max()]])
    np.testing.assert_allclose(out[1, 0],
                               [[img[2:4, 2:4].max(), img[2:4, 4:6].max()],
                                [img[4:6, 2:4].max(), img[4:6, 4:6].max()]])


def test_ssd_head_forward():
    """detection_output: decode + NMS over a tiny SSD head, end to end."""
    rng = np.random.RandomState(7)
    P, C = 8, 4
    prior = _rand_boxes(rng, P)
    pvar = np.full((P, 4), 0.1, "float32")
    loc = rng.normal(0, 0.1, (1, P, 4)).astype("float32")
    scores = rng.rand(1, C, P).astype("float32")

    def build():
        pb = layers.data("pb", shape=[4])
        pv = layers.data("pv", shape=[4])
        lc = layers.data("lc", shape=[P, 4])
        sc = layers.data("sc", shape=[C, P])
        out = layers.detection_output(lc, sc, pb, pv, score_threshold=0.2,
                                      nms_top_k=6, keep_top_k=5,
                                      nms_threshold=0.45)
        return [out]

    out, = _run(build, {"pb": prior, "pv": pvar, "lc": loc, "sc": scores})
    rows = np.asarray(out.data)[0]
    count = int(np.asarray(out.lens)[0])
    assert 0 < count <= 5
    assert np.all(rows[:count, 0] >= 1)          # no background detections
    assert np.all(rows[:count, 1] > 0.2)         # above score threshold
    # scores sorted descending
    assert np.all(np.diff(rows[:count, 1]) <= 1e-6)