"""v2 trainer event loop + DetectionMAP evaluator tests.

Reference: python/paddle/v2/trainer.py:137-215 (SGD.train event stream),
v2/event.py, evaluator.py DetectionMAP / operators/detection_map_op.cc.
"""

import io

import numpy as np

import paddle_tpu.fluid as fluid
import paddle_tpu.v2 as v2
import paddle_tpu.reader as reader_pkg

layers = fluid.layers


def _make_trainer(metrics=True):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8])
        label = layers.data("label", shape=[1], dtype="int64")
        logits = layers.fc(x, size=3, act="softmax")
        cost = layers.mean(layers.cross_entropy(logits, label))
        acc = layers.accuracy(input=logits, label=label)
        trainer = v2.SGD(cost=cost,
                         optimizer=fluid.optimizer.Adam(learning_rate=0.05),
                         feed_order=["x", "label"],
                         metrics={"acc": acc} if metrics else None,
                         main_program=main, startup_program=startup)
    return trainer


def _dataset(n=256, seed=0):
    # one fixed labeling rule; `seed` only varies the sampled inputs
    w = np.random.RandomState(42).normal(0, 1, (8, 3))
    rng = np.random.RandomState(seed)
    xs = rng.normal(0, 1, (n, 8)).astype("float32")
    ys = (xs @ w).argmax(axis=1).astype("int64").reshape(-1, 1)
    return [(xs[i], ys[i]) for i in range(n)]


def test_v2_event_loop_trains_and_fires_events():
    trainer = _make_trainer()
    data = _dataset()
    rd = reader_pkg.batch(lambda: iter(data), batch_size=32)

    events = []
    costs = []

    def handler(evt):
        events.append(type(evt).__name__)
        if isinstance(evt, v2.event.EndIteration):
            costs.append(evt.cost)
            assert "acc" in evt.metrics
        if isinstance(evt, v2.event.EndPass):
            assert "cost" in evt.metrics and "acc" in evt.metrics

    trainer.train(reader=rd, num_passes=3, event_handler=handler)
    # event protocol: BeginPass .. (BeginIteration EndIteration)* .. EndPass
    assert events[0] == "BeginPass" and events[-1] == "EndPass"
    assert events.count("BeginPass") == 3 and events.count("EndPass") == 3
    assert events.count("EndIteration") == 3 * 8
    assert costs[-1] < 0.4 * costs[0]  # it learns

    # held-out evaluation
    result = trainer.test(reader_pkg.batch(
        lambda: iter(_dataset(96, seed=1)), batch_size=32))
    assert isinstance(result, v2.event.TestResult)
    assert float(result.metrics["acc"]) > 0.8

    # parameters round-trip to a tar (v2 parameters.to_tar capability)
    buf = io.BytesIO()
    trainer.save_parameter_to_tar(buf)
    import tarfile
    names = tarfile.open(fileobj=io.BytesIO(buf.getvalue())).getnames()
    assert any(n.endswith(".npy") for n in names)
    assert "MANIFEST.json" in names


def test_detection_map_evaluator():
    from paddle_tpu.core.lod import LoDArray
    from paddle_tpu.fluid.evaluator import DetectionMAP
    import jax.numpy as jnp

    # 1 image, 2 gt boxes of class 1; detections: one perfect hit (score .9),
    # one miss (score .8), one duplicate of the hit (score .7 -> FP)
    gt = [[(1, 0.0, 0.0, 0.4, 0.4), (1, 0.5, 0.5, 0.9, 0.9)]]
    rows = np.array([[[1, 0.9, 0.0, 0.0, 0.4, 0.4],
                      [1, 0.8, 0.0, 0.6, 0.3, 0.95],
                      [1, 0.7, 0.01, 0.01, 0.41, 0.41]]], "float32")
    dets = LoDArray(jnp.asarray(rows), jnp.asarray([3], jnp.int32))
    ev = DetectionMAP(overlap_threshold=0.5)
    ev.update(dets, gt)
    m = ev.eval()
    # recall points: efter det1 (TP) r=.5 p=1; det2 FP; det3 FP
    # 11-pt AP = (6 points at p=1 for r<=0.5? r>=t for t in 0..0.5 -> p=1) /11
    exp = sum(1.0 if t <= 0.5 else 0.0 for t in np.linspace(0, 1, 11)) / 11
    np.testing.assert_allclose(m, exp, rtol=1e-6)

    # a second image with a clean hit raises the mAP
    ev.update(LoDArray(jnp.asarray(rows[:, :1]), jnp.asarray([1], jnp.int32)),
              [[(1, 0.0, 0.0, 0.4, 0.4)]])
    assert ev.eval() > m

def test_detection_map_voc_semantics():
    """Classes with gt but no detections contribute AP=0; duplicate
    detections of one matched gt are FPs (VOC matching), per the reference
    detection_map op."""
    from paddle_tpu.core.lod import LoDArray
    from paddle_tpu.fluid.evaluator import DetectionMAP
    import jax.numpy as jnp

    # gt classes {1, 2}; detector only ever finds class 1
    gt = [[(1, 0.0, 0.0, 0.4, 0.4), (2, 0.5, 0.5, 0.9, 0.9)]]
    rows = np.array([[[1, 0.9, 0.0, 0.0, 0.4, 0.4]]], "float32")
    ev = DetectionMAP(overlap_threshold=0.5)
    ev.update(LoDArray(jnp.asarray(rows), jnp.asarray([1], jnp.int32)), gt)
    # class 1 AP = 1.0, class 2 AP = 0 -> mAP 0.5 (not 1.0)
    np.testing.assert_allclose(ev.eval(), 0.5, rtol=1e-6)

    # two same-class gts, both detections centered on gt A: second is FP
    ev2 = DetectionMAP(overlap_threshold=0.5)
    gt2 = [[(1, 0.0, 0.0, 0.4, 0.4), (1, 0.05, 0.05, 0.45, 0.45)]]
    rows2 = np.array([[[1, 0.9, 0.0, 0.0, 0.4, 0.4],
                       [1, 0.8, 0.0, 0.0, 0.4, 0.4]]], "float32")
    ev2.update(LoDArray(jnp.asarray(rows2), jnp.asarray([2], jnp.int32)),
               gt2)
    flags = [tp for _, tp in ev2._dets[1]]
    assert flags == [True, False]  # duplicate does not steal gt B


def test_v2_ploter(tmp_path):
    """v2 plot.Ploter (reference python/paddle/v2/plot/plot.py +
    tests/test_ploter.py): named series accumulate; DISABLE_PLOT short-
    circuits rendering; with matplotlib available the curve saves to a
    file from a trainer event handler."""
    import os

    os.environ["DISABLE_PLOT"] = "True"
    try:
        from paddle_tpu.v2.plot import Ploter
        p = Ploter("train cost", "test cost")
        p.append("train cost", 0, 1.5)
        p.append("train cost", 1, 1.2)
        p.append("test cost", 0, 1.7)
        assert getattr(p, "__plot_data__")["train cost"].value == [1.5, 1.2]
        p.plot()          # disabled: must be a no-op, not an import crash
        p.reset()
        assert getattr(p, "__plot_data__")["train cost"].step == []
    finally:
        del os.environ["DISABLE_PLOT"]

    try:
        import matplotlib  # noqa: F401
    except ImportError:
        return
    from paddle_tpu.v2.plot import Ploter
    trainer = _make_trainer()
    ploter = Ploter("train cost")

    def handler(evt):
        if isinstance(evt, v2.event.EndIteration):
            ploter.append("train cost", evt.batch_id, evt.cost)

    rd = reader_pkg.batch(lambda: iter(_dataset(64)), batch_size=32)
    trainer.train(reader=rd, num_passes=1, event_handler=handler)
    out = str(tmp_path / "curve.png")
    ploter.plot(path=out)
    assert os.path.getsize(out) > 0
