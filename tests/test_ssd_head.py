"""SSD composites: multi_box_head + ssd_loss + detection_map layer.

Reference: python/paddle/fluid/layers/detection.py (multi_box_head :568,
ssd_loss :350, detection_map :157) — the SSD training pipeline the
reference book-era models use, composed from the detection op family.
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


def _ssd_program(num_classes=3, priors=None):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 12
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 32, 32])
        feat = fluid.layers.conv2d(input=img, num_filters=8, filter_size=3,
                                   stride=4, padding=1, act="relu")
        locs, confs, box, var = layers.multi_box_head(
            inputs=[feat], image=img, base_size=32, num_classes=num_classes,
            aspect_ratios=[[2.0]], min_sizes=[8.0], max_sizes=[16.0],
            flip=True, clip=True)
        gt_box = fluid.layers.data("gt_box", shape=[4], lod_level=1)
        gt_label = fluid.layers.data("gt_label", shape=[1], dtype="int64",
                                     lod_level=1)
        loss_rows = layers.ssd_loss(locs, confs, gt_box, gt_label, box, var)
        loss = fluid.layers.reduce_sum(loss_rows)
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss, startup)
    return main, startup, (img, gt_box, gt_label), (locs, confs, box, loss)


def test_multi_box_head_shapes():
    main, startup, _, (locs, confs, box, _loss) = _ssd_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    feeder_img = np.random.RandomState(0).rand(2, 3, 32, 32).astype(
        "float32")
    gt = [(np.array([[0.1, 0.1, 0.4, 0.4]], "float32"),
           np.array([[1]], "int64")) for _ in range(2)]
    feeder = fluid.DataFeeder([main.global_block().var("gt_box"),
                               main.global_block().var("gt_label")], main)
    feed = feeder.feed(gt)
    feed["img"] = feeder_img
    lv, cv, bv = exe.run(main, feed=feed,
                         fetch_list=[locs, confs, box], scope=scope)
    lv, cv, bv = map(np.asarray, (lv, cv, bv))
    # 8x8 cells x 4 priors/cell (min, sqrt(min*max), ar=2 flipped pair)
    assert bv.shape == (8 * 8 * 4, 4)
    assert lv.shape == (2, bv.shape[0], 4)
    assert cv.shape == (2, bv.shape[0], 3)
    # clipped normalized boxes
    assert bv.min() >= 0.0 and bv.max() <= 1.0


def test_ssd_loss_trains():
    """The SSD objective must be finite and decrease while fitting a fixed
    ground-truth box (locs/confs convs moving toward the targets)."""
    main, startup, (img, gt_box, gt_label), (_l, _c, _b, loss) = \
        _ssd_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(3)
    imgs = rng.rand(4, 3, 32, 32).astype("float32")
    gt = [(np.array([[0.2, 0.2, 0.5, 0.5]], "float32"),
           np.array([[2]], "int64")) for _ in range(4)]
    feeder = fluid.DataFeeder([gt_box, gt_label], main)
    feed = feeder.feed(gt)
    feed["img"] = imgs

    first = last = None
    for _ in range(25):
        v, = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
        last = float(np.asarray(v))
        assert np.isfinite(last)
        first = last if first is None else first
    assert last < 0.7 * first, (first, last)


def test_detection_map_layer():
    """detection_map as a graph op: perfect detections -> mAP 1.0."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        det = fluid.layers.data("det", shape=[6], lod_level=1)
        lab = fluid.layers.data("lab", shape=[5], lod_level=1)
        m = layers.detection_map(det, lab, class_num=3,
                                 overlap_threshold=0.5)
    exe = fluid.Executor(fluid.CPUPlace(), mode="eager")
    feeder = fluid.DataFeeder([main.global_block().var("det"),
                               main.global_block().var("lab")], main)
    box = [0.1, 0.1, 0.4, 0.4]
    feed = feeder.feed([(
        np.array([[1.0, 0.9] + box], "float32"),       # label,score,box
        np.array([[1.0] + box], "float32"),            # label,box
    )])
    out, = exe.run(main, feed=feed, fetch_list=[m])
    np.testing.assert_allclose(np.asarray(out).reshape(-1), [1.0],
                               atol=1e-6)


def test_multi_box_head_flip_dedup_matches_prior_count():
    """Regression (round-5 review): aspect_ratios [2.0, 0.5] with flip=True
    must NOT double-count 0.5 (the op dedups it against 1/2.0) — conv
    channels and emitted priors stay aligned."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 32, 32])
        feat = fluid.layers.conv2d(input=img, num_filters=4, filter_size=3,
                                   stride=4, padding=1, act=None)
        locs, confs, box, var = layers.multi_box_head(
            inputs=[feat], image=img, base_size=32, num_classes=2,
            aspect_ratios=[[2.0, 0.5]], min_sizes=[8.0], max_sizes=[16.0],
            flip=True)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    imgs = np.random.RandomState(1).rand(1, 3, 32, 32).astype("float32")
    lv, bv = exe.run(main, feed={"img": imgs}, fetch_list=[locs, box],
                     scope=scope)
    lv, bv = np.asarray(lv), np.asarray(bv)
    assert lv.shape[1] == bv.shape[0], (lv.shape, bv.shape)
