"""Reference-checkpoint gate-permutation helpers (paddle_tpu.utils)."""

import numpy as np

from paddle_tpu.utils import (convert_reference_lstm_weight,
                              convert_reference_lstm_bias)


def test_weight_roundtrip():
    rng = np.random.RandomState(3)
    H = 8
    w_ref = rng.normal(size=(H, 4 * H)).astype("float32")
    ours = convert_reference_lstm_weight(w_ref)
    back = convert_reference_lstm_weight(ours, inverse=True)
    assert np.array_equal(back, w_ref)
    # ref blocks [c, i, f, o] land at ours [i, f, c, o]
    c, i, f, o = np.split(w_ref, 4, axis=1)
    np.testing.assert_array_equal(ours, np.concatenate([i, f, c, o], axis=1))


def test_bias_plain_and_peephole():
    rng = np.random.RandomState(5)
    H = 4  # multiple of 4 on purpose: 7H is also divisible by 4
    b_ref = rng.normal(size=(1, 4 * H)).astype("float32")
    c, i, f, o = np.split(b_ref, 4, axis=1)
    np.testing.assert_array_equal(convert_reference_lstm_bias(b_ref),
                                  np.concatenate([i, f, c, o], axis=1))

    bp_ref = rng.normal(size=(1, 7 * H)).astype("float32")
    out = convert_reference_lstm_bias(bp_ref, peepholes=True)
    # gate blocks permuted, peephole tail untouched
    np.testing.assert_array_equal(out[:, 4 * H:], bp_ref[:, 4 * H:])
    c, i, f, o = np.split(bp_ref[:, :4 * H], 4, axis=1)
    np.testing.assert_array_equal(out[:, :4 * H],
                                  np.concatenate([i, f, c, o], axis=1))
    back = convert_reference_lstm_bias(out, peepholes=True, inverse=True)
    np.testing.assert_array_equal(back, bp_ref)
