"""CSP channels + Go blocks.

Reference: framework/channel_impl.h (buffered/unbuffered send/recv/close
semantics, framework/channel_test.cc pins them), operators/go_op.cc,
python/paddle/fluid/concurrency.py — the canonical use is a producer Go
block feeding training through a channel (concurrency_test.cc).
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid


def test_buffered_channel_fifo_and_close_semantics():
    ch = fluid.make_channel("int32", capacity=4)
    for i in range(4):
        fluid.channel_send(ch, i)
    fluid.channel_close(ch)
    # drains in order after close, then reports not-ok
    got = []
    while True:
        v, ok = fluid.channel_recv(ch)
        if not ok:
            break
        got.append(v)
    assert got == [0, 1, 2, 3]
    with pytest.raises(fluid.concurrency.ChannelClosed):
        fluid.channel_send(ch, 99)


def test_unbuffered_channel_rendezvous():
    ch = fluid.make_channel("float32", capacity=0)
    order = []

    with fluid.Go() as g:
        @g.run
        def producer():
            order.append("send-start")
            fluid.channel_send(ch, 1.0)
            order.append("send-done")

        import time
        time.sleep(0.2)
        # unbuffered: the send cannot complete before this recv
        assert "send-done" not in order
        v, ok = fluid.channel_recv(ch)
        assert ok and v == 1.0
        g.join(5.0)
    assert order == ["send-start", "send-done"]


def test_go_producer_feeds_training_through_channel():
    """The reference concurrency_test.cc pattern: a Go producer streams
    batches through a channel while the main thread trains."""
    layers = fluid.layers
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[6])
        y = layers.data("y", shape=[1])
        pred = layers.fc(x, size=1, act=None)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss, startup)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    rng = np.random.RandomState(0)
    w_true = rng.normal(0, 1, (6, 1)).astype("float32")
    ch = fluid.make_channel("float32", capacity=2)

    with fluid.Go() as g:
        @g.run
        def producer():
            r = np.random.RandomState(1)
            for _ in range(30):
                X = r.normal(0, 1, (32, 6)).astype("float32")
                fluid.channel_send(ch, (X, X @ w_true))
            fluid.channel_close(ch)

        losses = []
        while True:
            batch, ok = fluid.channel_recv(ch)
            if not ok:
                break
            X, Y = batch
            losses.append(float(exe.run(main, feed={"x": X, "y": Y},
                                        fetch_list=[loss])[0]))
        g.join(5.0)
    assert len(losses) == 30
    assert losses[-1] < 0.05 * losses[0]
