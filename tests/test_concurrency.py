"""CSP channels + Go blocks.

Reference: framework/channel_impl.h (buffered/unbuffered send/recv/close
semantics, framework/channel_test.cc pins them), operators/go_op.cc,
python/paddle/fluid/concurrency.py — the canonical use is a producer Go
block feeding training through a channel (concurrency_test.cc).
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid


def test_buffered_channel_fifo_and_close_semantics():
    ch = fluid.make_channel("int32", capacity=4)
    for i in range(4):
        fluid.channel_send(ch, i)
    fluid.channel_close(ch)
    # drains in order after close, then reports not-ok
    got = []
    while True:
        v, ok = fluid.channel_recv(ch)
        if not ok:
            break
        got.append(v)
    assert got == [0, 1, 2, 3]
    with pytest.raises(fluid.concurrency.ChannelClosed):
        fluid.channel_send(ch, 99)


def test_unbuffered_channel_rendezvous():
    ch = fluid.make_channel("float32", capacity=0)
    order = []

    with fluid.Go() as g:
        @g.run
        def producer():
            order.append("send-start")
            fluid.channel_send(ch, 1.0)
            order.append("send-done")

        import time
        time.sleep(0.2)
        # unbuffered: the send cannot complete before this recv
        assert "send-done" not in order
        v, ok = fluid.channel_recv(ch)
        assert ok and v == 1.0
        g.join(5.0)
    assert order == ["send-start", "send-done"]


def test_go_producer_feeds_training_through_channel():
    """The reference concurrency_test.cc pattern: a Go producer streams
    batches through a channel while the main thread trains."""
    layers = fluid.layers
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[6])
        y = layers.data("y", shape=[1])
        pred = layers.fc(x, size=1, act=None)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss, startup)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    rng = np.random.RandomState(0)
    w_true = rng.normal(0, 1, (6, 1)).astype("float32")
    ch = fluid.make_channel("float32", capacity=2)

    with fluid.Go() as g:
        @g.run
        def producer():
            r = np.random.RandomState(1)
            for _ in range(30):
                X = r.normal(0, 1, (32, 6)).astype("float32")
                fluid.channel_send(ch, (X, X @ w_true))
            fluid.channel_close(ch)

        losses = []
        while True:
            batch, ok = fluid.channel_recv(ch)
            if not ok:
                break
            X, Y = batch
            losses.append(float(exe.run(main, feed={"x": X, "y": Y},
                                        fetch_list=[loss])[0]))
        g.join(5.0)
    assert len(losses) == 30
    assert losses[-1] < 0.05 * losses[0]


# ---------------------------------------------------------------------------
# Select (reference fluid/concurrency.py:193, operators/select_op.cc;
# reference test: test_concurrency.py fibonacci via select send/recv cases)
# ---------------------------------------------------------------------------

def test_select_fibonacci():
    """The reference's CSP fibonacci: a select alternating a send of the
    running term with a recv on the quit channel."""
    ch = fluid.make_channel("int64", capacity=1)
    quit_ch = fluid.make_channel("int64", capacity=1)
    result = []

    with fluid.Go() as g:
        @g.run
        def consumer():
            for _ in range(10):
                v, ok = fluid.channel_recv(ch)
                result.append(v)
            fluid.channel_send(quit_ch, 0)

        x, y = 0, 1
        done = False
        while not done:
            sel = fluid.Select()

            @sel.case(fluid.channel_send, ch, x)
            def send_case():
                pass

            @sel.case(fluid.channel_recv, quit_ch)
            def quit_case(value, ok):
                nonlocal done
                done = True

            fired = sel.run(timeout=10.0)
            if fired == 0:
                x, y = y, x + y
        g.join(5.0)

    assert result == [0, 1, 1, 2, 3, 5, 8, 13, 21, 34]


def test_select_default_case():
    ch = fluid.make_channel("float32", capacity=1)
    hits = []

    sel = fluid.Select()

    @sel.case(fluid.channel_recv, ch)
    def recv_case(value, ok):
        hits.append(("recv", value, ok))

    @sel.default
    def default_case():
        hits.append(("default",))

    # nothing ready -> default fires immediately
    assert sel.run() == 1
    assert hits == [("default",)]

    # now make the recv case ready: first-ready wins over default
    fluid.channel_send(ch, 7.0)
    assert sel.run() == 0
    assert hits[-1] == ("recv", 7.0, True)


def test_select_first_ready_ordering():
    a = fluid.make_channel("int64", capacity=1)
    b = fluid.make_channel("int64", capacity=1)
    fluid.channel_send(b, 2)

    sel = fluid.Select()
    got = []

    @sel.case(fluid.channel_recv, a)
    def case_a(value, ok):
        got.append(("a", value))

    @sel.case(fluid.channel_recv, b)
    def case_b(value, ok):
        got.append(("b", value))

    assert sel.run(timeout=1.0) == 1
    assert got == [("b", 2)]

    # closed-and-drained channels are READY with ok=False (select wakes on
    # close, channel_impl.h close notifies all waiters)
    fluid.channel_close(a)
    assert sel.run(timeout=1.0) == 0
    assert got[-1] == ("a", None)
