"""The kernel tier's selection/fallback contract (ops/pallas/__init__.py).

Covers: kernel_tier flag resolution (auto|pallas|jnp), the deprecated
use_pallas_rnn/use_pallas_ctc flags still forcing their kernels (with a
one-time DeprecationWarning), the silent per-kernel fallback counter for
unsupported shapes, the Executor jit-cache keying on the tier flag, and
the kernel-tier capability surfaces (ModelRegistry manifests,
InferenceEngine.stats()).
"""

import warnings

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.ops import pallas as tier


@pytest.fixture(autouse=True)
def _reset():
    yield
    fluid.set_flags({"kernel_tier": "auto", "use_pallas_rnn": False,
                     "use_pallas_ctc": False})
    tier.reset_fallback_counts()


def test_auto_resolves_jnp_on_cpu():
    fluid.set_flags({"kernel_tier": "auto"})
    assert tier.resolve_tier() == "jnp"  # the suite runs on CPU
    assert not tier.use_pallas("lstm")
    assert not tier.use_pallas("conv_bn")


def test_explicit_tiers():
    fluid.set_flags({"kernel_tier": "pallas"})
    assert tier.resolve_tier() == "pallas"
    assert tier.use_pallas("gru")          # pallas = everywhere, even gru
    fluid.set_flags({"kernel_tier": "jnp"})
    assert tier.resolve_tier() == "jnp"
    assert not tier.use_pallas("lstm")


def test_invalid_tier_raises():
    fluid.set_flags({"kernel_tier": "cuda"})
    with pytest.raises(ValueError, match="kernel_tier"):
        tier.resolve_tier()
    with pytest.raises(ValueError, match="kernel_tier"):
        tier.use_pallas("lstm")


def test_legacy_flag_forces_pallas_with_deprecation_warning():
    tier._warned_legacy.clear()
    fluid.set_flags({"kernel_tier": "jnp", "use_pallas_rnn": True})
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert tier.use_pallas("lstm")       # legacy True wins over jnp
        assert tier.use_pallas("gru")        # same flag covers gru
        assert tier.use_pallas("lstm")
    deps = [w for w in caught if issubclass(w.category, DeprecationWarning)]
    assert len(deps) == 1, "deprecation warning must fire exactly once"
    assert "use_pallas_rnn" in str(deps[0].message)
    assert "kernel_tier" in str(deps[0].message)


def test_unsupported_shape_falls_back_with_counter_bump():
    fluid.set_flags({"kernel_tier": "pallas"})
    tier.reset_fallback_counts()
    assert not tier.use_pallas("conv_bn", supported=False)
    assert not tier.use_pallas("conv_bn", supported=False)
    assert not tier.use_pallas("optimizer", supported=False)
    assert tier.fallback_counts() == {"conv_bn": 2, "optimizer": 1}
    # a supported dispatch does not bump
    assert tier.use_pallas("conv_bn", supported=True)
    assert tier.fallback_counts()["conv_bn"] == 2
    # under a jnp tier nothing asks for pallas, so nothing is a fallback
    fluid.set_flags({"kernel_tier": "jnp"})
    tier.reset_fallback_counts()
    assert not tier.use_pallas("conv_bn", supported=False)
    assert tier.fallback_counts() == {}


def test_executor_jit_key_includes_kernel_tier():
    from paddle_tpu.core import executor as ex
    assert "kernel_tier" in ex._JIT_KEY_FLAGS
    fluid.set_flags({"kernel_tier": "jnp"})
    k1 = ex._jit_flag_key()
    fluid.set_flags({"kernel_tier": "pallas"})
    k2 = ex._jit_flag_key()
    assert k1 != k2, "a tier flip must retrace (distinct jit cache keys)"


def _save_tiny_model(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.fc(input=x, size=3, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    d = str(tmp_path / "model")
    fluid.io.save_inference_model(d, ["x"], [y], exe, main, scope=scope)
    return d


def test_registry_manifest_and_engine_stats_carry_kernel_tier(tmp_path):
    from paddle_tpu.serving import InferenceEngine, ModelRegistry

    model_dir = _save_tiny_model(tmp_path)
    reg = ModelRegistry(str(tmp_path / "registry"))
    v = reg.publish("m", model_dir)                  # defaults to active tier
    assert reg.manifest("m", v)["kernel_tier"] == tier.resolve_tier()
    v2 = reg.publish("m", model_dir, kernel_tier="pallas")
    assert reg.manifest("m", v2)["kernel_tier"] == "pallas"
    with pytest.raises(ValueError, match="kernel_tier"):
        reg.publish("m", model_dir, kernel_tier="cuda")
    # the failed publish must not leave a torn version dir that bricks
    # the next publish of that version number
    v3 = reg.publish("m", model_dir)
    assert v3 == v2 + 1
    # verify() still passes: the capability field rides the manifest but
    # the content hash covers the bundle files only
    reg.verify("m", v2)

    eng = InferenceEngine(model_dir, buckets="1,2")
    assert eng.stats()["kernel_tier"] == tier.resolve_tier()
    # warmup re-samples the tier: an engine warmed under jnp says so
    fluid.set_flags({"kernel_tier": "jnp"})
    eng.warmup()
    st = eng.stats()
    assert st["kernel_tier"] == "jnp"
    assert st["warmed"]


def test_profiler_spans_distinguish_tiers():
    """Dispatch sites wrap in pallas/<kernel> vs jnp/<kernel> spans
    (kind="kernel"), so chrome traces attribute tier time per op."""
    from paddle_tpu.core import profiler
    from paddle_tpu.ops.pallas import kernel_span

    profiler.enable_profiler()
    try:
        with kernel_span("pallas", "conv_bn"):
            pass
        with kernel_span("jnp", "optimizer"):
            pass
        evs = profiler.events()
    finally:
        profiler.disable_profiler(sorted_key=None)
    names = {(kind, name) for kind, name, *_ in evs}
    assert ("kernel", "pallas/conv_bn") in names
    assert ("kernel", "jnp/optimizer") in names


def test_lstm_op_runs_under_pallas_tier():
    """kernel_tier=pallas engages the whole-recurrence LSTM kernel through
    the op layer (interpret on CPU) and matches the jnp tier."""
    def run(tier_name):
        fluid.set_flags({"kernel_tier": tier_name})
        from paddle_tpu.fluid import framework
        framework.reset_unique_name()
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 11
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[1], dtype="int64", lod_level=1)
            e = fluid.layers.embedding(x, size=[10, 8])
            proj = fluid.layers.fc(e, size=8 * 4)
            h, _ = fluid.layers.dynamic_lstm(proj, size=8 * 4)
            pred = fluid.layers.fc(fluid.layers.sequence_last_step(h),
                                   size=1)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        rng = np.random.RandomState(2)
        seqs = [rng.randint(0, 10, (ln, 1)).astype("int64")
                for ln in (3, 5, 2)]
        return exe.run(main, feed={"x": seqs}, fetch_list=[pred],
                       scope=scope)[0]

    base = run("jnp")
    pallas = run("pallas")
    np.testing.assert_allclose(pallas, base, rtol=5e-3, atol=1e-4)
