"""Loss/softmax op tests (reference test_softmax_op.py,
test_cross_entropy_op.py, test_softmax_with_cross_entropy_op.py,
test_sigmoid_cross_entropy_with_logits_op.py)."""

import numpy as np

from op_test import OpTest


def np_softmax(x):
    e = np.exp(x - x.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)


class TestSoftmax(OpTest):
    op_type = "softmax"

    def setup(self):
        x = np.random.uniform(0.1, 1, (5, 7)).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {}
        self.outputs = {"Out": np_softmax(x)}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X"], "Out", max_relative_error=0.01)


class TestCrossEntropy(OpTest):
    op_type = "cross_entropy"

    def setup(self):
        batch, classes = 6, 9
        x = np_softmax(np.random.uniform(0.1, 1, (batch, classes))
                       .astype("float32"))
        label = np.random.randint(0, classes, (batch, 1)).astype("int64")
        y = -np.log(x[np.arange(batch), label.flatten()]).reshape(batch, 1)
        self.inputs = {"X": x, "Label": label}
        self.attrs = {}
        self.outputs = {"Y": y}

    def test_output(self):
        self.setup()
        self.check_output()


class TestCrossEntropySoft(OpTest):
    op_type = "cross_entropy"

    def setup(self):
        batch, classes = 5, 7
        x = np_softmax(np.random.uniform(0.1, 1, (batch, classes))
                       .astype("float32"))
        label = np.random.uniform(0.1, 1, (batch, classes)).astype("float32")
        label /= label.sum(axis=1, keepdims=True)
        y = (-label * np.log(x)).sum(axis=1, keepdims=True)
        self.inputs = {"X": x, "Label": label}
        self.attrs = {"soft_label": True}
        self.outputs = {"Y": y}

    def test_output(self):
        self.setup()
        self.check_output()


class TestSoftmaxWithCrossEntropy(OpTest):
    op_type = "softmax_with_cross_entropy"

    def setup(self):
        batch, classes = 6, 10
        logits = np.random.uniform(0.1, 1, (batch, classes)).astype("float32")
        sm = np_softmax(logits)
        label = np.random.randint(0, classes, (batch, 1)).astype("int64")
        loss = -np.log(sm[np.arange(batch), label.flatten()]).reshape(batch, 1)
        self.inputs = {"Logits": logits, "Label": label}
        self.attrs = {}
        self.outputs = {"Softmax": sm, "Loss": loss}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["Logits"], "Loss", max_relative_error=0.02)


class TestSigmoidCrossEntropyWithLogits(OpTest):
    op_type = "sigmoid_cross_entropy_with_logits"

    def setup(self):
        x = np.random.uniform(-2, 2, (5, 8)).astype("float32")
        label = np.random.randint(0, 2, (5, 8)).astype("float32")
        out = np.maximum(x, 0) - x * label + np.log(1 + np.exp(-np.abs(x)))
        self.inputs = {"X": x, "Label": label}
        self.attrs = {}
        self.outputs = {"Out": out}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X"], "Out", max_relative_error=0.01)


class TestMean(OpTest):
    op_type = "mean"

    def setup(self):
        x = np.random.random((7, 9)).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {}
        self.outputs = {"Out": np.mean(x)}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X"], "Out")


class TestHuberLoss(OpTest):
    op_type = "huber_loss"

    def setup(self):
        x = np.random.uniform(0, 1, (6, 1)).astype("float32")
        y = np.random.uniform(0, 1, (6, 1)).astype("float32")
        delta = 0.5
        r = y - x
        loss = np.where(np.abs(r) <= delta, 0.5 * r * r,
                        delta * (np.abs(r) - 0.5 * delta))
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"delta": delta}
        self.outputs = {"Residual": r, "Out": loss}

    def test_output(self):
        self.setup()
        self.check_output()
