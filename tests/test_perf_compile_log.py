"""Compile telemetry (obs.perf): every compiled-executable build lands a
``paddle_tpu_compile_seconds`` observation + CompileRecord + ``compile``
flight event; engine warmup yields exactly one per executable; steady-
state dispatch yields ZERO (the zero-retrace invariant, now observable);
the layer's flags are NOT in the executor jit key (flipping never
retraces).
"""

import json

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.obs import perf
from paddle_tpu.obs.metrics import REGISTRY
from paddle_tpu.obs.recorder import RECORDER
from paddle_tpu.testing.models import build_mlp, export_tiny_lm, mlp_feed


@pytest.fixture(autouse=True)
def _fresh_perf_log():
    perf.COMPILE_LOG.clear()
    RECORDER.clear()
    yield
    perf.COMPILE_LOG.clear()
    RECORDER.clear()


def _export_mlp(tmp_path, **kw):
    main, startup, _loss, logits = build_mlp(return_logits=True, **kw)
    exe = fluid.Executor()
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    d = str(tmp_path / "bundle")
    fluid.io.save_inference_model(d, ["img"], [logits], exe, main,
                                  scope=scope)
    return d


# ---------------------------------------------------------------------------
# executor-level telemetry
# ---------------------------------------------------------------------------

def test_jit_build_lands_record_histogram_and_flight_event():
    main, startup, loss = build_mlp()
    exe = fluid.Executor()
    scope = fluid.Scope()
    hist = REGISTRY.get("paddle_tpu_compile_seconds")
    before = hist.total()
    exe.run(startup, scope=scope)
    exe.run(main, feed=mlp_feed(4), fetch_list=[loss], scope=scope)
    recs = perf.COMPILE_LOG.records()
    # startup block + training step = two compiled-executable builds
    assert len(recs) == 2
    assert all(r.site == "jit_step" for r in recs)
    assert all(r.seconds > 0 for r in recs)
    step = recs[-1]
    assert step.identity["feeds"]["img"] == [4, 16]
    assert "program_version" in step.identity
    assert hist.total() == before + 2
    events = RECORDER.events(kinds={"compile"})
    assert len(events) == 2
    assert events[-1]["component"] == "jit_step"
    assert events[-1]["detail"]["seconds"] > 0
    # records and dumps are json-safe end to end
    json.dumps([r.as_dict() for r in recs])
    # steady state: replaying the same shapes adds NOTHING
    n = perf.COMPILE_LOG.stats()["count"]
    for _ in range(3):
        exe.run(main, feed=mlp_feed(4), fetch_list=[loss], scope=scope)
    assert perf.COMPILE_LOG.stats()["count"] == n
    # a NEW batch shape is an internal jit retrace of the same compiled
    # fn — the build-time retrace counter misses it, this layer must not
    exe.run(main, feed=mlp_feed(6), fetch_list=[loss], scope=scope)
    assert perf.COMPILE_LOG.stats()["count"] == n + 1


def test_run_steps_scan_attributed_to_jit_scan():
    main, startup, loss = build_mlp()
    exe = fluid.Executor()
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    perf.COMPILE_LOG.clear()
    exe.run_steps(main, feeds=[mlp_feed(4), mlp_feed(4, seed=1)],
                  fetch_list=[loss], scope=scope, steps=2)
    sites = [r.site for r in perf.COMPILE_LOG.records()]
    assert sites == ["jit_scan"]


def test_flag_off_disables_layer_and_never_retraces():
    from paddle_tpu.core.executor import _JIT_KEY_FLAGS
    assert "obs_compile_log" not in _JIT_KEY_FLAGS
    assert "obs_compile_cost" not in _JIT_KEY_FLAGS

    main, startup, loss = build_mlp()
    exe = fluid.Executor()
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    exe.run(main, feed=mlp_feed(4), fetch_list=[loss], scope=scope)
    retraces = REGISTRY.get("paddle_tpu_executor_retraces").total()
    n = perf.COMPILE_LOG.stats()["count"]
    fluid.set_flags({"obs_compile_log": 0})
    try:
        assert not perf.enabled()
        # flipping the layer off must not retrace the cached step...
        exe.run(main, feed=mlp_feed(4), fetch_list=[loss], scope=scope)
        assert REGISTRY.get("paddle_tpu_executor_retraces").total() \
            == retraces
        # ...and a build while off records nothing anywhere
        ev_before = len(RECORDER.events(kinds={"compile"}))
        exe.run(main, feed=mlp_feed(8), fetch_list=[loss], scope=scope)
        assert perf.COMPILE_LOG.stats()["count"] == n
        assert len(RECORDER.events(kinds={"compile"})) == ev_before
    finally:
        fluid.set_flags({"obs_compile_log": 256})
    # back on: the layer resumes without retracing the old shapes
    exe.run(main, feed=mlp_feed(4), fetch_list=[loss], scope=scope)
    assert REGISTRY.get("paddle_tpu_executor_retraces").total() == retraces


def test_obs_compile_cost_harvests_cost_analysis():
    main, startup, loss = build_mlp(hidden=8, seed=11)
    exe = fluid.Executor()
    scope = fluid.Scope()
    fluid.set_flags({"obs_compile_cost": True})
    try:
        exe.run(startup, scope=scope)
        exe.run(main, feed=mlp_feed(4), fetch_list=[loss], scope=scope)
    finally:
        fluid.set_flags({"obs_compile_cost": False})
    step = perf.COMPILE_LOG.records()[-1]
    # the CPU backend provides cost_analysis — flops/bytes must land
    assert step.flops is not None and step.flops > 0
    assert step.bytes_accessed is not None and step.bytes_accessed > 0


def test_compile_log_ring_bounded_and_stats():
    log = perf.CompileLog(capacity=3)
    for i in range(5):
        log.add(perf.CompileRecord("jit_step", 0.5, identity={"i": i}))
    recs = log.records()
    assert len(recs) == 3
    assert [r.identity["i"] for r in recs] == [2, 3, 4]
    st = log.stats()
    assert st["count"] == 5                       # lifetime, not window
    assert st["total_seconds"] == pytest.approx(2.5)
    assert st["by_site"]["jit_step"]["count"] == 3
    log.clear()
    assert log.records() == [] and log.stats()["count"] == 0


# ---------------------------------------------------------------------------
# engine warmup: exactly one record + one event per executable
# ---------------------------------------------------------------------------

def test_inference_engine_warmup_one_record_per_executable(tmp_path):
    from paddle_tpu.serving import InferenceEngine
    d = _export_mlp(tmp_path)
    perf.COMPILE_LOG.clear()
    RECORDER.clear()
    eng = InferenceEngine(d, buckets=[1, 2, 4])
    compiled = eng.warmup()
    assert compiled == 3
    recs = perf.COMPILE_LOG.records()
    assert len(recs) == 3
    assert [r.site for r in recs] == ["engine_warmup"] * 3
    assert sorted(r.identity["bucket"] for r in recs) == [1, 2, 4]
    assert len(RECORDER.events(kinds={"compile"})) == 3
    # steady state: dispatches through every bucket add ZERO
    n = perf.COMPILE_LOG.stats()["count"]
    for rows in (1, 2, 3, 4, 2):
        eng.infer({"img": np.zeros((rows, 16), np.float32)})
    assert perf.COMPILE_LOG.stats()["count"] == n
    assert eng.hot_recompiles == 0


def test_generation_engine_warmup_one_record_per_executable(tmp_path):
    from paddle_tpu.serving.generate import GenerationEngine
    d = str(tmp_path / "lm")
    export_tiny_lm(d)
    perf.COMPILE_LOG.clear()
    RECORDER.clear()
    eng = GenerationEngine(d, max_seqs=2, max_len=32, num_blocks=32)
    compiled = eng.warmup()
    recs = perf.COMPILE_LOG.records()
    # one per executable: the decode step + every prefill bucket
    assert compiled == len(recs) == 4
    assert all(r.site == "genengine_warmup" for r in recs)
    phases = sorted((r.identity["phase"], r.identity["bucket"])
                    for r in recs)
    assert phases == [("decode", 2), ("prefill", 8), ("prefill", 16),
                      ("prefill", 32)]
    assert len(RECORDER.events(kinds={"compile"})) == 4
    # steady state: a full generate (prefill + decode steps) adds ZERO
    n = perf.COMPILE_LOG.stats()["count"]
    handle, _toks, finished = eng.start([1, 2, 3], 4)
    while not finished:
        finished = any(f for _h, _t, f in eng.step())
    assert perf.COMPILE_LOG.stats()["count"] == n
    assert eng.hot_recompiles == 0
