"""AOT StableHLO inference export + the C API example end-to-end.

Reference parity: paddle/fluid/inference (save/Load + run without the
training program) and paddle/capi with its dense model_inference example
(capi/examples/model_inference/dense/main.c) — here the artifact is a
serialized jax.export StableHLO computation and the C layer embeds
CPython (paddle_tpu/capi/).
"""

import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import aot

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _train_small_model(seed=0):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, 16, act="relu")
        probs = fluid.layers.fc(h, 4, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(probs, label))
        fluid.optimizer.SGD(0.1).minimize(loss, startup)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(3)
    W = rng.normal(0, 1, (8, 4))
    for _ in range(30):
        X = rng.normal(0, 1, (32, 8)).astype("float32")
        y = np.argmax(X @ W, 1).astype("int64").reshape(-1, 1)
        exe.run(main, feed={"x": X, "label": y}, fetch_list=[loss],
                scope=scope)
    return main, exe, scope, probs


def test_aot_export_roundtrip_and_batch_polymorphism():
    main, exe, scope, probs = _train_small_model()
    X = np.random.RandomState(9).normal(0, 1, (6, 8)).astype("float32")
    # reference output from the PRUNED inference slice (running the full
    # main program would take another optimizer step and move the params)
    from paddle_tpu.fluid.io import _prune_program
    infer_prog = _prune_program(main, ["x"], [probs.name])
    ref = exe.run(infer_prog, feed={"x": X}, fetch_list=[probs.name],
                  scope=scope)[0]

    d = tempfile.mkdtemp()
    manifest = aot.export_inference_artifact(d, ["x"], [probs], exe,
                                             main_program=main, scope=scope)
    assert manifest["format"].startswith("jax.export.stablehlo")
    assert os.path.exists(os.path.join(d, aot.ARTIFACT_FILENAME))

    art = aot.load_inference_artifact(d)
    out = art.run({"x": X})[0]
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    # one artifact serves other batch sizes (symbolic batch dim)
    X2 = X[:2]
    out2 = art.run({"x": X2})[0]
    np.testing.assert_allclose(out2, ref[:2], rtol=1e-5, atol=1e-6)

    # the artifact is self-contained: a FRESH process with no program or
    # scope reproduces the same outputs
    code = (
        "import numpy as np, os\n"
        "os.environ['JAX_PLATFORMS']='cpu'\n"
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "from paddle_tpu.fluid import aot\n"
        f"art = aot.load_inference_artifact({d!r})\n"
        "X = np.load(os.path.join({d!r}, 'x.npy'))\n"
        "out = art.run({'x': X})[0]\n"
        "np.save(os.path.join({d!r}, 'out.npy'), out)\n"
        "print('FRESH_OK')\n").replace("{d!r}", repr(d))
    np.save(os.path.join(d, "x.npy"), X)
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=180)
    assert "FRESH_OK" in r.stdout, r.stdout + r.stderr
    np.testing.assert_allclose(np.load(os.path.join(d, "out.npy")), ref,
                               rtol=1e-5, atol=1e-6)


def _compile_capi_example(example, binname, extra=()):
    import shutil
    if shutil.which("gcc") is None:
        pytest.skip("no C compiler")
    capi = os.path.join(REPO, "paddle_tpu", "capi")
    bindir = tempfile.mkdtemp()
    binpath = os.path.join(bindir, binname)
    cflags = subprocess.check_output(
        ["python3-config", "--includes"], text=True).split()
    ldflags = subprocess.check_output(
        ["python3-config", "--embed", "--ldflags"], text=True).split()
    cmd = (["gcc", "-O1", "-o", binpath,
            os.path.join(capi, "examples/model_inference", example, "main.c"),
            os.path.join(capi, "paddle_tpu_capi.c")]
           + cflags + ldflags + list(extra))
    r = subprocess.run(cmd, capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    return binpath



def test_capi_dense_example_end_to_end():
    """Compile paddle_tpu/capi (gcc + embedded CPython) and run the dense
    example binary against a freshly exported artifact."""
    import shutil
    if shutil.which("gcc") is None:
        pytest.skip("no C compiler")

    main, exe, scope, probs = _train_small_model(seed=1)
    d = tempfile.mkdtemp()
    aot.export_inference_artifact(d, ["x"], [probs], exe,
                                  main_program=main, scope=scope)

    binpath = _compile_capi_example("dense", "dense_infer")

    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run([binpath, d, "8"], env=env, capture_output=True,
                       text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "DENSE_INFER_OK" in r.stdout, r.stdout + r.stderr
    # softmax row sums to 1
    sum_line = [l for l in r.stdout.splitlines() if l.startswith("sum:")][0]
    assert abs(float(sum_line.split()[1]) - 1.0) < 1e-4, r.stdout


def test_aot_export_lod_model():
    """A sequence model (embedding -> LSTM -> last step -> softmax) exports
    with symbolic batch AND padded-length dims; the artifact serves ragged
    feeds of any shape."""
    import paddle_tpu.dataset  # noqa: F401  (module import sanity)

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 4
    with fluid.program_guard(main, startup):
        words = fluid.layers.data("words", shape=[1], dtype="int64",
                                  lod_level=1)
        emb = fluid.layers.embedding(words, size=(50, 8))
        proj = fluid.layers.fc(emb, 16 * 4)
        h, _ = fluid.layers.dynamic_lstm(proj, size=16 * 4)
        last = fluid.layers.sequence_last_step(h)
        probs = fluid.layers.fc(last, 3, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(0)
    seqs = [rng.randint(0, 50, (int(n), 1)).astype("int64")
            for n in (4, 7, 3)]
    ref = exe.run(main, feed={"words": seqs}, fetch_list=[probs],
                  scope=scope)[0]

    d = tempfile.mkdtemp()
    aot.export_inference_artifact(d, ["words"], [probs], exe,
                                  main_program=main, scope=scope)
    art = aot.load_inference_artifact(d)
    out = art.run({"words": seqs})[0]
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    # different batch AND different max_len through the same artifact
    seqs2 = [rng.randint(0, 50, (9, 1)).astype("int64")]
    out2 = art.run({"words": seqs2})[0]
    assert out2.shape == (1, 3)
    np.testing.assert_allclose(out2.sum(1), 1.0, atol=1e-5)


def test_capi_sequence_example_end_to_end():
    """The sequence C example (reference capi/examples/model_inference/
    sequence/main.c): ragged int64 token sequences through
    pd_tpu_model_run_seq, checked against the in-process artifact run."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 6
    with fluid.program_guard(main, startup):
        words = fluid.layers.data("words", shape=[1], dtype="int64",
                                  lod_level=1)
        emb = fluid.layers.embedding(words, size=(20, 8))
        pooled = fluid.layers.sequence_pool(emb, pool_type="average")
        probs = fluid.layers.fc(pooled, 3, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)

    d = tempfile.mkdtemp()
    aot.export_inference_artifact(d, ["words"], [probs], exe,
                                  main_program=main, scope=scope)

    # in-process expectation for the example's hard-coded sequences
    art = aot.load_inference_artifact(d)
    seqs = [np.array(s, "int64").reshape(-1, 1)
            for s in ([1, 2, 3, 4], [5, 6], [7, 8, 9])]
    want = art.run({"words": seqs})[0]

    binpath = _compile_capi_example("sequence", "seq_infer")
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run([binpath, d], env=env, capture_output=True,
                       text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "SEQ_INFER_OK" in r.stdout, r.stdout + r.stderr
    rows = [l for l in r.stdout.splitlines() if l.startswith("seq ")]
    assert len(rows) == 3
    got = np.array([[float(v) for v in l.split(":")[1].split("(")[0].split()]
                    for l in rows])
    np.testing.assert_allclose(got, np.asarray(want), rtol=1e-4, atol=1e-5)


def test_capi_multi_thread_example_end_to_end():
    """The multi-thread C example (reference capi/examples/model_inference/
    multi_thread/main.c:29-35): 4 pthreads forwarding concurrently on ONE
    loaded model; the GIL contract is documented in paddle_tpu_capi.h and
    each thread asserts its own runs are valid + deterministic."""
    main, exe, scope, probs = _train_small_model(seed=2)
    d = tempfile.mkdtemp()
    aot.export_inference_artifact(d, ["x"], [probs], exe,
                                  main_program=main, scope=scope)

    binpath = _compile_capi_example("multi_thread", "multi_thread_infer",
                                    extra=["-lpthread"])
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run([binpath, d, "8"], env=env, capture_output=True,
                       text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "MULTI_THREAD_INFER_OK" in r.stdout, r.stdout + r.stderr
    ok_lines = [l for l in r.stdout.splitlines() if "ok=1" in l]
    assert len(ok_lines) == 4, r.stdout
