"""CTC op tests against a brute-force / numpy reference.

Mirrors /root/reference/python/paddle/fluid/tests/unittests/test_warpctc_op.py
(python CTC forward as ground truth), test_ctc_align_op.py and
test_edit_distance_op.py.
"""

import itertools

import numpy as np
import pytest

from op_test import OpTest


def softmax(x):
    e = np.exp(x - x.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)


def ctc_loss_brute(logits, label, blank):
    """-log P(label | logits) by enumerating all alignments. logits [T, C]."""
    T, C = logits.shape
    p = softmax(logits)
    U = len(label)
    total = 0.0
    # enumerate paths of length T over the C symbols whose collapse == label
    for path in itertools.product(range(C), repeat=T):
        collapsed = []
        prev = None
        for s in path:
            if s != prev and s != blank:
                collapsed.append(s)
            prev = s
        if collapsed == list(label):
            prob = 1.0
            for t, s in enumerate(path):
                prob *= p[t, s]
            total += prob
    return -np.log(total)


def ctc_loss_dp(logits, label, blank):
    """Standard CTC forward DP (log space not needed at test sizes)."""
    T, C = logits.shape
    p = softmax(logits)
    z = []
    for l in label:
        z += [blank, l]
    z.append(blank)
    S = len(z)
    alpha = np.zeros((T, S))
    alpha[0, 0] = p[0, z[0]]
    if S > 1:
        alpha[0, 1] = p[0, z[1]]
    for t in range(1, T):
        for s in range(S):
            a = alpha[t - 1, s]
            if s >= 1:
                a += alpha[t - 1, s - 1]
            if s >= 2 and z[s] != blank and z[s] != z[s - 2]:
                a += alpha[t - 1, s - 2]
            alpha[t, s] = a * p[t, z[s]]
    total = alpha[T - 1, S - 1] + (alpha[T - 1, S - 2] if S > 1 else 0.0)
    return -np.log(total)


class TestWarpCTC(OpTest):
    op_type = "warpctc"

    def setup_method(self, method):
        rng = np.random.RandomState(7)
        C, blank = 5, 0
        logits_lod = [[0, 4, 9]]
        label_lod = [[0, 2, 4]]
        logits = rng.uniform(-1, 1, (9, C)).astype("float32")
        labels = np.array([[1], [2], [3], [4]], dtype="int64")
        losses = []
        for i in range(2):
            lg = logits[logits_lod[0][i]:logits_lod[0][i + 1]]
            lb = labels[label_lod[0][i]:label_lod[0][i + 1], 0]
            losses.append([ctc_loss_dp(lg, lb, blank)])
        self.inputs = {"Logits": (logits, logits_lod),
                       "Label": (labels, label_lod)}
        self.attrs = {"blank": blank, "norm_by_times": False}
        self.outputs = {"Loss": np.array(losses, dtype="float32")}

    def test_output(self):
        self.check_output(atol=1e-4)

    def test_output_matches_brute_force(self):
        rng = np.random.RandomState(11)
        lg = rng.uniform(-1, 1, (4, 3)).astype("float32")
        assert np.allclose(ctc_loss_dp(lg, [1, 2], 0),
                           ctc_loss_brute(lg, [1, 2], 0), atol=1e-6)

    def test_grad(self):
        self.check_grad(["Logits"], "Loss", max_relative_error=0.01)


class TestWarpCTCNormByTimes(OpTest):
    """norm_by_times=True must leave the forward Loss unscaled (the reference
    scales only the logits gradient: warpctc_op.h:217-223)."""
    op_type = "warpctc"

    def setup_method(self, method):
        rng = np.random.RandomState(13)
        C, blank = 4, 0
        logits_lod = [[0, 3, 8]]
        label_lod = [[0, 1, 3]]
        logits = rng.uniform(-1, 1, (8, C)).astype("float32")
        labels = np.array([[1], [2], [3]], dtype="int64")
        losses = []
        for i in range(2):
            lg = logits[logits_lod[0][i]:logits_lod[0][i + 1]]
            lb = labels[label_lod[0][i]:label_lod[0][i + 1], 0]
            losses.append([ctc_loss_dp(lg, lb, blank)])
        self.inputs = {"Logits": (logits, logits_lod),
                       "Label": (labels, label_lod)}
        self.attrs = {"blank": blank, "norm_by_times": True}
        self.outputs = {"Loss": np.array(losses, dtype="float32")}

    def test_output_unscaled(self):
        self.check_output(atol=1e-4)

    def test_grad_is_scaled(self):
        """Analytic grad with norm_by_times=True == (grad without) / T."""
        import jax
        import paddle_tpu.fluid as fluid
        from paddle_tpu.core.lod import flat_to_lodarray

        grads = {}
        for norm in (False, True):
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                lg = fluid.layers.data("lg", shape=[4], lod_level=1)
                lb = fluid.layers.data("lb", shape=[1], dtype="int64",
                                       lod_level=1)
                loss = fluid.layers.warpctc(input=lg, label=lb, blank=0,
                                            norm_by_times=norm)
                total = fluid.layers.mean(loss)
                fluid.backward.append_backward(total)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            out = exe.run(
                main,
                feed={"lg": (self.inputs["Logits"][0],
                             self.inputs["Logits"][1]),
                      "lb": (self.inputs["Label"][0],
                             self.inputs["Label"][1])},
                fetch_list=["lg@GRAD"], return_numpy=False)
            grads[norm] = out[0]
        g0, g1 = grads[False].data, grads[True].data
        lens = np.asarray(grads[False].lens)
        expected = np.asarray(g0) / lens[:, None, None]
        assert np.allclose(np.asarray(g1), expected, atol=1e-6)


class TestCTCAlign(OpTest):
    op_type = "ctc_align"

    def setup_method(self, method):
        x = np.array([[0, 1, 1, 0, 2, 2, 0],
                      [3, 0, 3, 3, 0, 0, 0]], dtype="int32").reshape(2, 7, 1)
        lod = [[0, 7, 11]]
        xs = np.concatenate([x[0, :7], x[1, :4]], axis=0)
        self.inputs = {"Input": (xs, lod)}
        self.attrs = {"blank": 0, "merge_repeated": True}
        out = np.array([[1, 2], [3, 3]], dtype="int32").reshape(-1, 1)
        self.outputs = {"Output": (out.reshape(4, 1), [[0, 2, 4]])}

    def test_output(self):
        self.check_output()


class TestEditDistance(OpTest):
    op_type = "edit_distance"

    def setup_method(self, method):
        hyp = np.array([[1], [2], [3], [1], [2]], dtype="int64")
        ref = np.array([[1], [3], [1], [2], [4]], dtype="int64")
        hyp_lod = [[0, 3, 5]]
        ref_lod = [[0, 2, 5]]
        # seq0: [1,2,3] vs [1,3] -> 1 ; seq1: [1,2] vs [1,2,4] -> 1
        self.inputs = {"Hyps": (hyp, hyp_lod), "Refs": (ref, ref_lod)}
        self.attrs = {"normalized": False}
        self.outputs = {"Out": np.array([[1.0], [1.0]], dtype="float32")}

    def test_output(self):
        self.check_output(no_check_set=["SequenceNum"])
