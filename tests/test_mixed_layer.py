"""mixed_layer + projections, and the reference's OWN sample trainer config.

Reference: trainer_config_helpers/layers.py:867 (mixed_layer),
full/trans_full/identity/table/dotmul projections, and
paddle/trainer/tests/sample_trainer_config.conf — the C++ trainer's test
config (8 fc variants + a 9-way mixed layer with a SHARED transposed
weight) must build and train VERBATIM.
"""

import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.v2.config_helpers import parse_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF_CONF = "/root/reference/paddle/trainer/tests/sample_trainer_config.conf"
needs_ref = pytest.mark.skipif(not os.path.exists(REF_CONF),
                               reason="reference tree not available")


def test_mixed_layer_sums_projections():
    """mixed = act(full(x1) + trans_full(x2, shared) + identity(x3))."""
    from paddle_tpu.v2.config_helpers import (
        LayerOutput, full_matrix_projection, identity_projection,
        mixed_layer)

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        a = fluid.layers.data("a", shape=[4])
        b = fluid.layers.data("b", shape=[3])
        with mixed_layer(size=3, act=None) as m:
            m += full_matrix_projection(input=LayerOutput(a, size=4))
            m += identity_projection(input=LayerOutput(b, size=3))
        out = m.var

    exe = fluid.Executor(fluid.CPUPlace(), mode="eager")
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    av = rng.randn(2, 4).astype("float32")
    bv = rng.randn(2, 3).astype("float32")
    got, = exe.run(main, feed={"a": av, "b": bv}, fetch_list=[out],
                   scope=scope)
    w = np.asarray(scope.find_var(
        main.global_block().all_parameters()[0].name))
    np.testing.assert_allclose(np.asarray(got), av @ w + bv, rtol=1e-5)


def test_trans_full_projection_shares_weight():
    """The sample_trainer_config 'sharew' pattern: an fc's weight reused
    transposed inside mixed — one parameter, both paths."""
    from paddle_tpu.v2.config_helpers import (
        LayerOutput, ParameterAttribute, fc_layer, mixed_layer,
        trans_full_matrix_projection)

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3])
        lo = LayerOutput(x, size=3)
        fc4 = fc_layer(input=lo, size=5, bias_attr=False,
                       param_attr=ParameterAttribute(name="sharew"))
        with mixed_layer(size=3, act=None) as m:
            m += trans_full_matrix_projection(
                input=fc4, param_attr=ParameterAttribute(name="sharew"))
        out = m.var
    params = [p.name for p in main.global_block().all_parameters()]
    assert params.count("sharew") >= 1
    assert len(set(params)) == 1  # ONLY sharew exists

    exe = fluid.Executor(fluid.CPUPlace(), mode="eager")
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    xv = np.random.RandomState(1).randn(2, 3).astype("float32")
    got, = exe.run(main, feed={"x": xv}, fetch_list=[out], scope=scope)
    w = np.asarray(scope.find_var("sharew"))           # [3, 5]
    np.testing.assert_allclose(np.asarray(got), (xv @ w) @ w.T, rtol=1e-5)


@needs_ref
def test_reference_sample_trainer_config_builds_and_trains(tmp_path):
    """The C++ trainer's own test config, verbatim: parse + 2 CLI passes."""
    shutil.copyfile(REF_CONF, tmp_path / "cfg.py")
    topo, main, startup = parse_config(str(tmp_path / "cfg.py"))
    types = [op.type for op in main.global_block().ops]
    assert types.count("mul") >= 9          # 8 fc + mixed projections
    assert "matmul" in types                # the transposed shared weight
    # shared weight used by BOTH fc4 and the trans projection
    params = [p.name for p in main.global_block().all_parameters()]
    assert "sharew" in params

    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.v2.trainer_cli",
         "--config=cfg.py", "--job=train", "--num_passes=2"],
        env=env, capture_output=True, text=True, timeout=300,
        cwd=str(tmp_path))
    assert r.returncode == 0, r.stdout + r.stderr
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("Pass")]
    assert len(lines) == 2
    costs = [float(ln.split("cost=")[1]) for ln in lines]
    assert costs[1] < costs[0], costs


@needs_ref
@pytest.mark.parametrize("conf", ["test_config.conf",
                                  "sample_trainer_config_parallel.conf"])
def test_reference_trainer_test_configs_build(conf):
    """The C++ trainer's other test configs build verbatim: test_config
    (asymmetric cudnn pooling over a non-square flat input, weighted
    classification cost, nce_layer, shared trans projection) and the
    parallel variant."""
    topo, main, _startup = parse_config(
        f"/root/reference/paddle/trainer/tests/{conf}")
    assert len(main.global_block().ops) > 10
    if conf == "test_config.conf":
        types = [op.type for op in main.global_block().ops]
        assert "pool2d" in types and "nce" in types and "matmul" in types


def test_hsigmoid_numeric_and_grad():
    """hsigmoid vs a numpy SimpleCode reference (MatrixBitCode.cpp:
    c = label + C, node = (c>>(b+1))-1, bit = (c>>b)&1,
    cost = sum softplus(z) - bit*z)."""
    from op_test import OpTest

    rng = np.random.RandomState(7)
    C, D, B = 5, 4, 6
    x = rng.randn(B, D).astype("float32")
    w = rng.randn(C - 1, D).astype("float32") * 0.5
    bias = rng.randn(1, C - 1).astype("float32") * 0.1
    label = rng.randint(0, C, (B, 1)).astype("int64")

    def ref_cost():
        out = np.zeros((B, 1), "float64")
        for i in range(B):
            c = int(label[i, 0]) + C
            b = 0
            while (c >> (b + 1)) >= 1:
                idx = (c >> (b + 1)) - 1
                bit = (c >> b) & 1
                z = float(x[i] @ w[idx] + bias[0, idx])
                out[i, 0] += np.log1p(np.exp(z)) - bit * z
                b += 1
        return out.astype("float32")

    t = OpTest()
    t.op_type = "hsigmoid"
    t.inputs = {"X": x, "W": w, "Label": label, "Bias": bias}
    t.attrs = {"num_classes": C}
    t.outputs = {"Out": ref_cost()}
    t.check_output(atol=1e-4, rtol=1e-3)
    t.check_grad(["X", "W", "Bias"], "Out", max_relative_error=0.05)


@needs_ref
def test_reference_hsigmoid_config_builds_and_trains(tmp_path):
    """sample_trainer_config_hsigmoid.conf — the last buildable C++ trainer
    test config — runs verbatim through the CLI (the reference's
    test_Trainer contract is run-to-completion; its synthetic labels are
    random, so descent isn't the gate — finite costs near ln(3) are)."""
    src = "/root/reference/paddle/trainer/tests/" \
          "sample_trainer_config_hsigmoid.conf"
    shutil.copyfile(src, tmp_path / "cfg.py")
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.v2.trainer_cli",
         "--config=cfg.py", "--job=train", "--num_passes=2"],
        env=env, capture_output=True, text=True, timeout=300,
        cwd=str(tmp_path))
    assert r.returncode == 0, r.stdout + r.stderr
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("Pass")]
    assert len(lines) == 2
    costs = [float(ln.split("cost=")[1]) for ln in lines]
    # 3-class hierarchical sigmoid on random labels sits near its ~2-bit
    # path cost; wildly larger values would mean broken code paths
    assert all(np.isfinite(c) and 0.2 < c < 3.0 for c in costs), costs


def test_identity_projection_size_mismatch_raises():
    """offset=None with in_size != out_size is a config error (reference
    config_assert), not a silent crop to the first out_size columns; an
    explicit offset selects a window as before."""
    from paddle_tpu.v2.config_helpers import (
        LayerOutput, identity_projection, mixed_layer)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        b = fluid.layers.data("b", shape=[5])
        with pytest.raises(ValueError, match="identity_projection"):
            with mixed_layer(size=3, act=None) as m:
                m += identity_projection(input=LayerOutput(b, size=5))

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        b = fluid.layers.data("b", shape=[5])
        with mixed_layer(size=3, act=None) as m:
            m += identity_projection(input=LayerOutput(b, size=5), offset=1)
        out = m.var
    exe = fluid.Executor(fluid.CPUPlace(), mode="eager")
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    bv = np.arange(10, dtype="float32").reshape(2, 5)
    got, = exe.run(main, feed={"b": bv}, fetch_list=[out], scope=scope)
    np.testing.assert_allclose(np.asarray(got), bv[:, 1:4])
