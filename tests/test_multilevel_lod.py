"""Nested (2-level) LoD tests.

Reference: framework/lod_tensor.h:55-107 — LoD is a vector of offset
levels; 2-level tensors group sequences into super-sequences (beam-search
output: [source][beam][tokens]; hierarchical text: [doc][sentence][words]).
Pinned here: feed/fetch round-trip in the reference's (flat, 2-level lod)
wire form, nested python-list feeds, sequence_expand with ref_level=0
(+ its gradient), and the 2-level LoD on beam_search_decode output.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu.fluid as fluid
from paddle_tpu.core.lod import (LoDArray, flat_to_lodarray,
                                 lodarray_to_flat, pack_sequences)

layers = fluid.layers


def test_flat_roundtrip_2level():
    # 2 outer sequences: first has 2 inner seqs (lens 2,3), second has 1 (len 2)
    flat = np.arange(14, dtype="float32").reshape(7, 2)
    lod = [[0, 2, 3], [0, 2, 5, 7]]
    arr = flat_to_lodarray(flat, lod)
    assert arr.lod_level == 2
    np.testing.assert_array_equal(np.asarray(arr.lens), [2, 3, 2])
    np.testing.assert_array_equal(np.asarray(arr.outer_lens), [2, 1])
    back, lod2 = lodarray_to_flat(arr)
    np.testing.assert_array_equal(back, flat)
    assert lod2 == [[0, 2, 3], [0, 2, 5, 7]]


def test_row_to_outer():
    arr = LoDArray(jnp.zeros((5, 3)), jnp.asarray([1, 2, 3, 1, 2]),
                   jnp.asarray([2, 1, 2]))
    np.testing.assert_array_equal(np.asarray(arr.row_to_outer()),
                                  [0, 0, 1, 2, 2])


def test_feed_fetch_2level_through_executor():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[1], dtype="int64", lod_level=2)
        out = layers.scale(x, scale=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    # nested python-list feed: 2 docs, [2, 1] sentences
    feed = {"x": [[np.array([[1], [2]], "int64"),
                   np.array([[3], [4], [5]], "int64")],
                  [np.array([[6], [7]], "int64")]]}
    got = exe.run(main, feed=feed, fetch_list=[out])[0]
    flat, lod = lodarray_to_flat(got)
    np.testing.assert_array_equal(flat[:, 0], [2, 4, 6, 8, 10, 12, 14])
    assert lod == [[0, 2, 3], [0, 2, 5, 7]]

    # reference wire-form feed: (flat array, 2-level lod)
    feed2 = {"x": (np.arange(1, 8).reshape(7, 1).astype("int64"),
                   [[0, 2, 3], [0, 2, 5, 7]])}
    got2 = exe.run(main, feed=feed2, fetch_list=[out])[0]
    flat2, lod2 = lodarray_to_flat(got2)
    np.testing.assert_array_equal(flat2, flat)
    assert lod2 == lod


def test_sequence_expand_ref_level0():
    """x [n_outer, feat] expands once per inner sequence of y (reference
    sequence_expand ref_level=0): numpy-checked, including the gradient."""
    x_np = np.array([[1.0, 10.0], [2.0, 20.0]], "float32")
    # y: 2 outer groups with [2, 3] inner sequences
    y_seqs = [[np.zeros((2, 1), "float32"), np.zeros((1, 1), "float32")],
              [np.zeros((3, 1), "float32"), np.zeros((2, 1), "float32"),
               np.zeros((1, 1), "float32")]]

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = layers.data("x", shape=[2], stop_gradient=False) \
            if False else layers.data("x", shape=[2])
        xv.stop_gradient = False
        yv = layers.data("y", shape=[1], lod_level=2)
        out = layers.sequence_expand(xv, yv, ref_level=0)
        loss = layers.mean(layers.elementwise_mul(out, out))
        fluid.append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    got, gx = exe.run(main, feed={"x": x_np, "y": y_seqs},
                      fetch_list=[out, "x@GRAD"])
    expect = x_np[[0, 0, 1, 1, 1]]
    np.testing.assert_allclose(np.asarray(got), expect)
    # d mean(out^2)/dx_i = sum over copies of 2*x_i / out.size
    n = expect.size
    exp_gx = np.stack([2 * 2 * x_np[0] / n, 3 * 2 * x_np[1] / n])
    np.testing.assert_allclose(np.asarray(gx), exp_gx, rtol=1e-5)


def test_beam_search_decode_emits_2level_lod():
    from paddle_tpu.ops.control_flow_ops import TensorArrayVal

    b, beam, T = 2, 3, 4
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(2, 9, (T, b, beam)).astype("int32"))
    parents = jnp.asarray(np.zeros((T, b, beam), "int32"))
    scores = jnp.asarray(rng.rand(b, beam).astype("float32"))

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids_arr = fluid.layers.create_array("int32", cap=T)
        par_arr = fluid.layers.create_array("int32", cap=T)
        sc = layers.data("sc", shape=[beam])
        sent_ids, sent_scores = fluid.layers.beam_search_decode(
            ids_arr, par_arr, sc, end_id=1)
    exe = fluid.Executor(fluid.CPUPlace(), mode="eager")
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    scope.set(ids_arr.name, TensorArrayVal(ids, jnp.asarray(T, jnp.int32)))
    scope.set(par_arr.name,
              TensorArrayVal(parents, jnp.asarray(T, jnp.int32)))
    out = exe.run(main, feed={"sc": np.asarray(scores)},
                  fetch_list=[sent_ids], scope=scope)[0]
    assert out.lod_level == 2
    np.testing.assert_array_equal(np.asarray(out.outer_lens), [beam, beam])
    flat, lod = lodarray_to_flat(out)
    assert len(lod) == 2
    assert lod[0] == [0, beam, 2 * beam]

# ---------------------------------------------------------------------------
# round 4: N-level LoD (the reference cap-free LoD = vector<Vector<size_t>>,
# framework/lod_tensor.h:55) + feed-side length bucketing (the TPU answer to
# shrink_rnn_memory_op.cc batch shrinking)
# ---------------------------------------------------------------------------

def test_flat_roundtrip_3level():
    # [paragraph][sentence][phrase][tokens]: 2 paragraphs -> 3 sentences ->
    # 5 phrases -> 11 tokens
    flat = np.arange(22, dtype="float32").reshape(11, 2)
    lod = [[0, 2, 3], [0, 2, 4, 5], [0, 2, 4, 7, 9, 11]]
    arr = flat_to_lodarray(flat, lod)
    assert arr.lod_level == 3
    np.testing.assert_array_equal(np.asarray(arr.lens), [2, 2, 3, 2, 2])
    outer = arr.outer_levels
    assert len(outer) == 2
    np.testing.assert_array_equal(np.asarray(outer[0]), [2, 1])
    np.testing.assert_array_equal(np.asarray(outer[1]), [2, 2, 1])
    back, lod2 = lodarray_to_flat(arr)
    np.testing.assert_array_equal(back, flat)
    assert lod2 == lod


def test_3level_feed_through_executor():
    """Nested python-list feed at depth 3 packs + fetches intact."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[1], lod_level=3)
        y = layers.scale(x, scale=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    feed_nested = [  # 2 paragraphs, each a list of sentences of phrases
        [[np.array([[1.0], [2.0]], "float32"),
          np.array([[3.0]], "float32")],
         [np.array([[4.0], [5.0]], "float32")]],
        [[np.array([[6.0]], "float32")]],
    ]
    got, = exe.run(main, feed={"x": feed_nested}, fetch_list=[y])
    flat, lod = lodarray_to_flat(got)
    np.testing.assert_allclose(flat.reshape(-1),
                               [2, 4, 6, 8, 10, 12])
    assert lod == [[0, 2, 3], [0, 2, 3, 4], [0, 2, 3, 5, 6]]


def test_lodarray_3level_pytree_roundtrip():
    import jax
    arr = LoDArray(jnp.ones((4, 3)), jnp.asarray([1, 2, 3, 1]),
                   (jnp.asarray([1, 1]), jnp.asarray([2, 2])))
    leaves, treedef = jax.tree_util.tree_flatten(arr)
    assert len(leaves) == 4
    back = jax.tree_util.tree_unflatten(treedef, leaves)
    assert back.lod_level == 3
    np.testing.assert_array_equal(np.asarray(back.outer_levels[1]), [2, 2])


def test_row_to_outer_multilevel():
    arr = LoDArray(jnp.zeros((5, 2)), jnp.asarray([1, 1, 1, 1, 1]),
                   (jnp.asarray([2, 1]), jnp.asarray([2, 1, 2])))
    # innermost outer level groups the 5 rows as [2, 1, 2]
    np.testing.assert_array_equal(np.asarray(arr.row_to_outer()),
                                  [0, 0, 1, 2, 2])
    # outermost level groups the 3 groups as [2, 1]
    np.testing.assert_array_equal(np.asarray(arr.row_to_outer(0)), [0, 0, 1])


def test_bucket_by_length():
    from paddle_tpu.reader import bucket_by_length, bucket_bound_for

    rng = np.random.RandomState(0)
    samples = [(list(range(n)),) for n in
               rng.randint(1, 40, size=50).tolist()]

    def reader():
        return iter(samples)

    bounds = [8, 16, 32, 64]
    batched = bucket_by_length(reader, key=lambda s: len(s[0]),
                               bucket_bounds=bounds, batch_size=4)
    seen = 0
    for batch in batched():
        seen += len(batch)
        lens = [len(s[0]) for s in batch]
        pad_to = bucket_bound_for(bounds, max(lens))
        # every sample in the batch fits its bucket bound, and the whole
        # batch shares one compiled shape
        assert all(l <= pad_to for l in lens)
        assert bucket_bound_for(bounds, max(lens)) == \
            bucket_bound_for(bounds, min(lens)) or len(set(
                bucket_bound_for(bounds, l) for l in lens)) == 1
    assert seen == 50  # nothing dropped

    # wasted-padding win vs padding every batch to the corpus bucket bound
    # (the compile-bounded no-bucketing baseline)
    corpus_max = max(len(s[0]) for s in samples)
    bucketed_steps = sum(
        len(b) * bucket_bound_for(bounds, max(len(s[0]) for s in b))
        for b in batched())
    flat_steps = 50 * bucket_bound_for(bounds, corpus_max)
    assert bucketed_steps < 0.7 * flat_steps


def test_2level_feed_with_empty_outer_group():
    """An empty outer sequence packs as a zero-length group (regression:
    the N-level peel must not stop at an empty first group)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[1], lod_level=2)
        y = layers.scale(x, scale=1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    feed = [[],  # first outer sequence empty
            [np.array([[1.0], [2.0]], "float32"),
             np.array([[3.0]], "float32")]]
    got, = exe.run(main, feed={"x": feed}, fetch_list=[y])
    flat, lod = lodarray_to_flat(got)
    np.testing.assert_allclose(flat.reshape(-1), [1, 2, 3])
    assert lod[0] == [0, 0, 2]


def test_sequence_expand_ref_level0_3level():
    """ref_level=0 must address the OUTERMOST level of a 3-level Y, and its
    gradient must segment-sum back to level-0 groups."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2])
        yv = layers.data("y", shape=[1], lod_level=3)
        out = layers.sequence_expand(x, yv, ref_level=0)
        loss = layers.mean(out)
        fluid.append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    x_np = np.array([[1.0, 10.0], [2.0, 20.0]], "float32")
    # 2 level-0 groups -> [2, 1] mid groups -> [1, 2, 2] rows
    y_feed = [
        [[np.array([[0.0]], "float32")],
         [np.array([[0.0]], "float32"), np.array([[0.0]], "float32")]],
        [[np.array([[0.0]], "float32"), np.array([[0.0]], "float32")]],
    ]
    got, dx = exe.run(main, feed={"x": x_np, "y": y_feed},
                      fetch_list=[out, fluid.grad_var_name("x")])
    # rows 0-2 belong to level-0 group 0; rows 3-4 to group 1
    np.testing.assert_allclose(np.asarray(got)[:, 0], [1, 1, 1, 2, 2])
    # d(mean)/dx: each of 5 rows x 2 cols contributes 1/10
    np.testing.assert_allclose(np.asarray(dx), [[0.3, 0.3], [0.2, 0.2]])


# ---------------------------------------------------------------------------
# variable-width LoD beam search (reference beam_search_op.cc; the ported
# case is operators/beam_search_op_test.cc verbatim)
# ---------------------------------------------------------------------------

def _run_lod_beam(cand_ids, cand_scores, outer, pre_ids, beam, end_id):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        b = main.global_block()
        for n in ("ids", "scores"):
            b.create_var(name=n, lod_level=2)
        b.create_var(name="pre_ids", shape=[-1, 1], dtype="int64",
                     is_data=True)
        for n in ("sel_ids", "sel_scores"):
            b.create_var(name=n, lod_level=2)
        b.append_op("beam_search",
                    {"pre_ids": ["pre_ids"], "ids": ["ids"],
                     "scores": ["scores"]},
                    {"selected_ids": ["sel_ids"],
                     "selected_scores": ["sel_scores"]},
                    {"beam_size": beam, "end_id": end_id, "level": 0})
    k = cand_ids.shape[1]
    ids_arr = LoDArray(jnp.asarray(cand_ids[:, :, None]),
                       jnp.full((len(cand_ids),), k, jnp.int32),
                       jnp.asarray(outer))
    sc_arr = LoDArray(jnp.asarray(cand_scores[:, :, None]),
                      jnp.full((len(cand_ids),), k, jnp.int32),
                      jnp.asarray(outer))
    exe = fluid.Executor(fluid.CPUPlace(), mode="eager")
    scope = fluid.Scope()
    scope.set("ids", ids_arr)
    scope.set("scores", sc_arr)
    got_ids, got_scores = exe.run(
        main, feed={"pre_ids": pre_ids.reshape(-1, 1)},
        fetch_list=["sel_ids", "sel_scores"], scope=scope,
        use_program_cache=False)
    return got_ids, got_scores


def test_beam_search_lod_reference_case():
    """operators/beam_search_op_test.cc: 2 sources with [1, 3] prefixes,
    K=3 candidates each, beam 2 -> data [2,4,3,8], scores [.3,.5,.9,.7],
    level1 widths [2,0,1,1] (prefix 1 retires: none of its candidates make
    the source's top-2)."""
    cand_ids = np.array([[4, 2, 5], [2, 1, 3], [3, 5, 2], [8, 2, 1]],
                        "int64")
    cand_scores = np.array([[0.5, 0.3, 0.2], [0.6, 0.3, 0.1],
                            [0.9, 0.5, 0.1], [0.7, 0.5, 0.1]], "float32")
    pre_ids = np.array([1, 2, 3, 4], "int64")
    got_ids, got_scores = _run_lod_beam(cand_ids, cand_scores, [1, 3],
                                        pre_ids, beam=2, end_id=0)
    flat, lod = lodarray_to_flat(got_ids)
    np.testing.assert_array_equal(flat.reshape(-1), [2, 4, 3, 8])
    sflat, slod = lodarray_to_flat(got_scores)
    np.testing.assert_allclose(sflat.reshape(-1), [0.3, 0.5, 0.9, 0.7])
    assert lod == slod == [[0, 1, 4], [0, 2, 2, 3, 4]]


def test_beam_search_lod_finished_prefix_leaves_beam():
    """A prefix whose pre_id == end_id contributes nothing, shrinking the
    live beam (beam_search_op.cc PruneEndidCandidates)."""
    cand_ids = np.array([[4, 2], [9, 7]], "int64")
    cand_scores = np.array([[0.9, 0.8], [0.95, 0.7]], "float32")
    pre_ids = np.array([1, 0], "int64")     # second prefix finished (end=0)
    got_ids, _ = _run_lod_beam(cand_ids, cand_scores, [2], pre_ids,
                               beam=3, end_id=0)
    flat, lod = lodarray_to_flat(got_ids)
    # top-3 across both prefixes = 9(.95), 4(.9), 2(.8); prefix 1's 9 is
    # then pruned -> only prefix 0's [2, 4] remain (id-ascending)
    np.testing.assert_array_equal(flat.reshape(-1), [2, 4])
    assert lod == [[0, 2], [0, 2, 2]]
