"""Nested (2-level) LoD tests.

Reference: framework/lod_tensor.h:55-107 — LoD is a vector of offset
levels; 2-level tensors group sequences into super-sequences (beam-search
output: [source][beam][tokens]; hierarchical text: [doc][sentence][words]).
Pinned here: feed/fetch round-trip in the reference's (flat, 2-level lod)
wire form, nested python-list feeds, sequence_expand with ref_level=0
(+ its gradient), and the 2-level LoD on beam_search_decode output.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu.fluid as fluid
from paddle_tpu.core.lod import (LoDArray, flat_to_lodarray,
                                 lodarray_to_flat, pack_sequences)

layers = fluid.layers


def test_flat_roundtrip_2level():
    # 2 outer sequences: first has 2 inner seqs (lens 2,3), second has 1 (len 2)
    flat = np.arange(14, dtype="float32").reshape(7, 2)
    lod = [[0, 2, 3], [0, 2, 5, 7]]
    arr = flat_to_lodarray(flat, lod)
    assert arr.lod_level == 2
    np.testing.assert_array_equal(np.asarray(arr.lens), [2, 3, 2])
    np.testing.assert_array_equal(np.asarray(arr.outer_lens), [2, 1])
    back, lod2 = lodarray_to_flat(arr)
    np.testing.assert_array_equal(back, flat)
    assert lod2 == [[0, 2, 3], [0, 2, 5, 7]]


def test_row_to_outer():
    arr = LoDArray(jnp.zeros((5, 3)), jnp.asarray([1, 2, 3, 1, 2]),
                   jnp.asarray([2, 1, 2]))
    np.testing.assert_array_equal(np.asarray(arr.row_to_outer()),
                                  [0, 0, 1, 2, 2])


def test_feed_fetch_2level_through_executor():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[1], dtype="int64", lod_level=2)
        out = layers.scale(x, scale=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    # nested python-list feed: 2 docs, [2, 1] sentences
    feed = {"x": [[np.array([[1], [2]], "int64"),
                   np.array([[3], [4], [5]], "int64")],
                  [np.array([[6], [7]], "int64")]]}
    got = exe.run(main, feed=feed, fetch_list=[out])[0]
    flat, lod = lodarray_to_flat(got)
    np.testing.assert_array_equal(flat[:, 0], [2, 4, 6, 8, 10, 12, 14])
    assert lod == [[0, 2, 3], [0, 2, 5, 7]]

    # reference wire-form feed: (flat array, 2-level lod)
    feed2 = {"x": (np.arange(1, 8).reshape(7, 1).astype("int64"),
                   [[0, 2, 3], [0, 2, 5, 7]])}
    got2 = exe.run(main, feed=feed2, fetch_list=[out])[0]
    flat2, lod2 = lodarray_to_flat(got2)
    np.testing.assert_array_equal(flat2, flat)
    assert lod2 == lod


def test_sequence_expand_ref_level0():
    """x [n_outer, feat] expands once per inner sequence of y (reference
    sequence_expand ref_level=0): numpy-checked, including the gradient."""
    x_np = np.array([[1.0, 10.0], [2.0, 20.0]], "float32")
    # y: 2 outer groups with [2, 3] inner sequences
    y_seqs = [[np.zeros((2, 1), "float32"), np.zeros((1, 1), "float32")],
              [np.zeros((3, 1), "float32"), np.zeros((2, 1), "float32"),
               np.zeros((1, 1), "float32")]]

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = layers.data("x", shape=[2], stop_gradient=False) \
            if False else layers.data("x", shape=[2])
        xv.stop_gradient = False
        yv = layers.data("y", shape=[1], lod_level=2)
        out = layers.sequence_expand(xv, yv, ref_level=0)
        loss = layers.mean(layers.elementwise_mul(out, out))
        fluid.append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    got, gx = exe.run(main, feed={"x": x_np, "y": y_seqs},
                      fetch_list=[out, "x@GRAD"])
    expect = x_np[[0, 0, 1, 1, 1]]
    np.testing.assert_allclose(np.asarray(got), expect)
    # d mean(out^2)/dx_i = sum over copies of 2*x_i / out.size
    n = expect.size
    exp_gx = np.stack([2 * 2 * x_np[0] / n, 3 * 2 * x_np[1] / n])
    np.testing.assert_allclose(np.asarray(gx), exp_gx, rtol=1e-5)


def test_beam_search_decode_emits_2level_lod():
    from paddle_tpu.ops.control_flow_ops import TensorArrayVal

    b, beam, T = 2, 3, 4
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(2, 9, (T, b, beam)).astype("int32"))
    parents = jnp.asarray(np.zeros((T, b, beam), "int32"))
    scores = jnp.asarray(rng.rand(b, beam).astype("float32"))

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids_arr = fluid.layers.create_array("int32", cap=T)
        par_arr = fluid.layers.create_array("int32", cap=T)
        sc = layers.data("sc", shape=[beam])
        sent_ids, sent_scores = fluid.layers.beam_search_decode(
            ids_arr, par_arr, sc, end_id=1)
    exe = fluid.Executor(fluid.CPUPlace(), mode="eager")
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    scope.set(ids_arr.name, TensorArrayVal(ids, jnp.asarray(T, jnp.int32)))
    scope.set(par_arr.name,
              TensorArrayVal(parents, jnp.asarray(T, jnp.int32)))
    out = exe.run(main, feed={"sc": np.asarray(scores)},
                  fetch_list=[sent_ids], scope=scope)[0]
    assert out.lod_level == 2
    np.testing.assert_array_equal(np.asarray(out.outer_lens), [beam, beam])
    flat, lod = lodarray_to_flat(out)
    assert len(lod) == 2
    assert lod[0] == [0, beam, 2 * beam]