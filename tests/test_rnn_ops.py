"""LSTM / GRU op tests against step-by-step numpy recurrences.

Mirrors /root/reference/python/paddle/fluid/tests/unittests/test_lstm_op.py
and test_gru_op.py in spirit: a python recurrence over each ragged sequence
is the ground truth. Gate layouts are this framework's documented contract
(ops/rnn_ops.py): LSTM [i, f, c, o]; GRU [u, r, c] with
h = u*c + (1-u)*h_prev (reference gru_unit_op.h: h = u*(c - h_prev) + h_prev).
"""

import numpy as np
import pytest

from op_test import OpTest


def sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def lstm_ref(x, lod, w, b):
    """x: [total, 4H] pre-projected; returns hidden/cell flat arrays."""
    H = w.shape[0]
    hs, cs = np.zeros((len(x), H), "float32"), np.zeros((len(x), H), "float32")
    offs = lod[0]
    for i in range(len(offs) - 1):
        h = np.zeros(H, "float32")
        c = np.zeros(H, "float32")
        for t in range(offs[i], offs[i + 1]):
            g = x[t] + h @ w + (b[0] if b is not None else 0.0)
            ig, fg = sigmoid(g[:H]), sigmoid(g[H:2 * H])
            cand, og = np.tanh(g[2 * H:3 * H]), sigmoid(g[3 * H:])
            c = fg * c + ig * cand
            h = og * np.tanh(c)
            hs[t], cs[t] = h, c
    return hs, cs


def gru_ref(x, lod, w, b):
    H = w.shape[0]
    hs = np.zeros((len(x), H), "float32")
    offs = lod[0]
    wu, wr, wc = w[:, :H], w[:, H:2 * H], w[:, 2 * H:]
    for i in range(len(offs) - 1):
        h = np.zeros(H, "float32")
        for t in range(offs[i], offs[i + 1]):
            g = x[t] + (b[0] if b is not None else 0.0)
            u = sigmoid(g[:H] + h @ wu)
            r = sigmoid(g[H:2 * H] + h @ wr)
            c = np.tanh(g[2 * H:] + (r * h) @ wc)
            h = u * c + (1 - u) * h
            hs[t] = h
    return hs


class TestLstm(OpTest):
    op_type = "lstm"

    def setup_method(self, method):
        rng = np.random.RandomState(21)
        H = 4
        lod = [[0, 3, 7]]
        x = rng.uniform(-0.5, 0.5, (7, 4 * H)).astype("float32")
        w = rng.uniform(-0.3, 0.3, (H, 4 * H)).astype("float32")
        b = rng.uniform(-0.2, 0.2, (1, 4 * H)).astype("float32")
        hs, cs = lstm_ref(x, lod, w, b)
        self.inputs = {"Input": (x, lod), "Weight": w, "Bias": b}
        self.attrs = {"use_peepholes": False, "is_reverse": False,
                      "gate_activation": "sigmoid",
                      "cell_activation": "tanh",
                      "candidate_activation": "tanh"}
        self.outputs = {"Hidden": (hs, lod), "Cell": (cs, lod)}

    def test_output(self):
        self.check_output(atol=1e-5)

    def test_grad(self):
        self.check_grad(["Input", "Weight", "Bias"], "Hidden",
                        max_relative_error=0.06)


class TestLstmReverse(OpTest):
    op_type = "lstm"

    def setup_method(self, method):
        rng = np.random.RandomState(23)
        H = 3
        lod = [[0, 2, 5]]
        x = rng.uniform(-0.5, 0.5, (5, 4 * H)).astype("float32")
        w = rng.uniform(-0.3, 0.3, (H, 4 * H)).astype("float32")
        b = rng.uniform(-0.2, 0.2, (1, 4 * H)).astype("float32")
        # reverse each sequence, run forward, reverse outputs back
        xr = x.copy()
        offs = lod[0]
        for i in range(len(offs) - 1):
            xr[offs[i]:offs[i + 1]] = x[offs[i]:offs[i + 1]][::-1]
        hs, cs = lstm_ref(xr, lod, w, b)
        for i in range(len(offs) - 1):
            hs[offs[i]:offs[i + 1]] = hs[offs[i]:offs[i + 1]][::-1]
            cs[offs[i]:offs[i + 1]] = cs[offs[i]:offs[i + 1]][::-1]
        self.inputs = {"Input": (x, lod), "Weight": w, "Bias": b}
        self.attrs = {"is_reverse": True}
        self.outputs = {"Hidden": (hs, lod), "Cell": (cs, lod)}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestGru(OpTest):
    op_type = "gru"

    def setup_method(self, method):
        rng = np.random.RandomState(29)
        H = 4
        lod = [[0, 3, 7]]
        x = rng.uniform(-0.5, 0.5, (7, 3 * H)).astype("float32")
        w = rng.uniform(-0.3, 0.3, (H, 3 * H)).astype("float32")
        b = rng.uniform(-0.2, 0.2, (1, 3 * H)).astype("float32")
        hs = gru_ref(x, lod, w, b)
        self.inputs = {"Input": (x, lod), "Weight": w, "Bias": b}
        self.attrs = {"is_reverse": False}
        self.outputs = {"Hidden": (hs, lod)}

    def test_output(self):
        self.check_output(atol=1e-5)

    def test_grad(self):
        self.check_grad(["Input", "Weight", "Bias"], "Hidden",
                        max_relative_error=0.06)


class TestLstmUnit(OpTest):
    op_type = "lstm_unit"

    def setup_method(self, method):
        rng = np.random.RandomState(31)
        b_, H = 5, 4
        x = rng.uniform(-0.5, 0.5, (b_, 4 * H)).astype("float32")
        c_prev = rng.uniform(-0.5, 0.5, (b_, H)).astype("float32")
        fb = 0.5
        i, f = sigmoid(x[:, :H]), sigmoid(x[:, H:2 * H] + fb)
        cand, o = np.tanh(x[:, 2 * H:3 * H]), sigmoid(x[:, 3 * H:])
        c = f * c_prev + i * cand
        h = o * np.tanh(c)
        self.inputs = {"X": x, "C_prev": c_prev}
        self.attrs = {"forget_bias": fb}
        self.outputs = {"C": c, "H": h}

    def test_output(self):
        self.check_output(atol=1e-5)

    def test_grad(self):
        self.check_grad(["X", "C_prev"], ["C", "H"],
                        max_relative_error=0.03)


class TestGruUnit(OpTest):
    op_type = "gru_unit"

    def setup_method(self, method):
        rng = np.random.RandomState(37)
        b_, H = 5, 4
        x = rng.uniform(-0.5, 0.5, (b_, 3 * H)).astype("float32")
        h_prev = rng.uniform(-0.5, 0.5, (b_, H)).astype("float32")
        w = rng.uniform(-0.3, 0.3, (H, 3 * H)).astype("float32")
        b = rng.uniform(-0.2, 0.2, (1, 3 * H)).astype("float32")
        g = x + b
        u = sigmoid(g[:, :H] + h_prev @ w[:, :H])
        r = sigmoid(g[:, H:2 * H] + h_prev @ w[:, H:2 * H])
        c = np.tanh(g[:, 2 * H:] + (r * h_prev) @ w[:, 2 * H:])
        h = u * c + (1 - u) * h_prev
        self.inputs = {"Input": x, "HiddenPrev": h_prev, "Weight": w,
                       "Bias": b}
        self.outputs = {"Gate": np.concatenate([u, r, c], axis=1),
                        "ResetHiddenPrev": r * h_prev, "Hidden": h}

    def test_output(self):
        self.check_output(atol=1e-5, no_check_set=["Gate", "ResetHiddenPrev"])

    def test_grad(self):
        self.check_grad(["Input", "HiddenPrev", "Weight"], "Hidden",
                        max_relative_error=0.06)


def test_dynamic_lstmp_trains_and_projects():
    """LSTM with recurrent projection (reference lstmp_op): the projection
    output has proj_size features, the recurrence runs over it, and the
    model trains end to end."""
    import paddle_tpu.fluid as fluid
    layers = fluid.layers
    H, P = 12, 5
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 6
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[1], dtype="int64", lod_level=1)
        e = layers.embedding(x, size=[10, 8])
        proj_in = layers.fc(e, size=4 * H)
        proj, cell = layers.dynamic_lstmp(proj_in, size=4 * H, proj_size=P)
        last = layers.sequence_last_step(proj)
        pred = layers.fc(last, size=1)
        label = layers.data("y", shape=[1])
        loss = layers.mean(layers.square(
            layers.elementwise_sub(pred, label)))
        fluid.optimizer.Adam(learning_rate=0.03).minimize(loss, startup)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(1)
    seqs = [rng.randint(0, 10, (int(rng.randint(2, 6)), 1)).astype("int64")
            for _ in range(6)]
    feed = {"x": seqs, "y": rng.normal(0, 1, (6, 1)).astype("float32")}
    out = exe.run(main, feed=feed, fetch_list=[proj, cell], scope=scope)
    assert out[0].data.shape[-1] == P       # projected width
    assert out[1].data.shape[-1] == H       # cell width
    losses = [float(exe.run(main, feed=feed, fetch_list=[loss],
                            scope=scope)[0]) for _ in range(40)]
    assert losses[-1] < 0.2 * losses[0], losses[::10]


def test_lstm_peepholes_train_and_differ_from_plain():
    """use_peepholes=True (the reference DEFAULT): i/f gates see the
    previous cell state, o sees the new one, weights live in the 7H bias
    (lstm_op.cc:74, math/detail/lstm_kernel.h:37-40). The model must train
    AND produce different outputs from the plain LSTM once the peephole
    weights move off zero."""
    import paddle_tpu.fluid as fluid
    layers = fluid.layers

    def build(peep):
        from paddle_tpu.fluid import framework
        framework.reset_unique_name()
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 4
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[1], dtype="int64", lod_level=1)
            e = layers.embedding(x, size=[10, 8])
            h, c = layers.dynamic_lstm(layers.fc(e, size=32), size=32,
                                       use_peepholes=peep)
            pred = layers.fc(layers.sequence_last_step(h), size=1)
            y = layers.data("y", shape=[1])
            loss = layers.mean(layers.square(
                layers.elementwise_sub(pred, y)))
            fluid.optimizer.Adam(learning_rate=0.05).minimize(loss, startup)
        return main, startup, loss

    rng = np.random.RandomState(0)
    seqs = [rng.randint(0, 10, (int(rng.randint(2, 6)), 1)).astype("int64")
            for _ in range(6)]
    feed = {"x": seqs, "y": rng.normal(0, 1, (6, 1)).astype("float32")}

    def train(peep):
        main, startup, loss = build(peep)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        losses = [float(exe.run(main, feed=feed, fetch_list=[loss],
                                scope=scope)[0]) for _ in range(40)]
        return main, scope, losses

    main, scope, losses = train(True)
    assert losses[-1] < 0.25 * losses[0], losses[::10]
    # the peephole bias is 7H wide and its diagonal weights trained away
    # from zero
    bias_name = [p.name for p in main.all_parameters()
                 if p.shape and p.shape[-1] == 7 * 8][0]
    b = np.asarray(scope.find_var(bias_name))
    assert np.abs(b[0, 4 * 8:]).max() > 1e-4
    # and the trajectory DIFFERS from the plain LSTM once peepholes move
    _, _, plain_losses = train(False)
    assert not np.allclose(losses[5:], plain_losses[5:], rtol=1e-4)


def test_simple_rnn_matches_numpy_and_trains():
    """Vanilla recurrence (v2 recurrent_layer): numpy-pinned forward over
    ragged lens, reversed variant, and gradient flow."""
    import paddle_tpu.fluid as fluid
    rng = np.random.RandomState(0)
    b, L, H = 3, 5, 4
    lens = np.array([5, 3, 4], "int32")
    seqs = [rng.normal(0, 1, (int(l), H)).astype("float32") for l in lens]

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[H], lod_level=1)
        out = fluid.layers.dynamic_vanilla_rnn(
            x, size=H, act="tanh",
            param_attr=fluid.ParamAttr(name="rw"),
            bias_attr=fluid.ParamAttr(name="rb"))
        rev = fluid.layers.dynamic_vanilla_rnn(
            x, size=H, act="tanh", is_reverse=True,
            param_attr=fluid.ParamAttr(name="rw"),
            bias_attr=fluid.ParamAttr(name="rb"))
        loss = fluid.layers.mean(fluid.layers.sequence_pool(out, "sum"))
        fluid.append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    got, got_rev, gw = exe.run(
        main, feed={"x": seqs}, fetch_list=[out, rev, "rw@GRAD"],
        scope=scope)

    w = np.asarray(scope.find_var("rw"))
    bias = np.asarray(scope.find_var("rb")).reshape(-1)

    def ref_run(seq):
        h = np.zeros(H, "float32")
        outs = []
        for t in range(len(seq)):
            h = np.tanh(seq[t] + bias + h @ w)
            outs.append(h)
        return np.stack(outs)

    from paddle_tpu.core.lod import lodarray_to_flat
    flat, _ = lodarray_to_flat(got)
    expect = np.concatenate([ref_run(s) for s in seqs])
    np.testing.assert_allclose(flat, expect, rtol=1e-5, atol=1e-6)

    # reversed recurrence = run on the flipped sequence, flip back
    flat_rev, _ = lodarray_to_flat(got_rev)
    expect_rev = np.concatenate([ref_run(s[::-1])[::-1] for s in seqs])
    np.testing.assert_allclose(flat_rev, expect_rev, rtol=1e-5, atol=1e-6)

    assert np.abs(np.asarray(gw)).sum() > 0  # gradient reaches the weight


def test_simple_rnn_without_bias():
    """bias_attr=False builds a bias-free recurrence (the reference
    recurrent_layer contract) and its parameter list has no bias."""
    import paddle_tpu.fluid as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], lod_level=1)
        out = fluid.layers.dynamic_vanilla_rnn(x, size=4, bias_attr=False)
        loss = fluid.layers.mean(fluid.layers.sequence_pool(out, "sum"))
        fluid.append_backward(loss)
    names = [p.name for p in main.all_parameters()]
    assert len(names) == 1 and not any("b_0" in n for n in names), names
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    seqs = [np.ones((3, 4), "float32")]
    got, gb = exe.run(main, feed={"x": seqs},
                      fetch_list=[loss, names[0] + "@GRAD"], scope=scope)
    assert np.isfinite(float(got))
    # grad restores the (size, size) parameter shape
    assert np.asarray(gb).shape == (4, 4)
