"""Parameter-server wire path: framed tensor codec, sparse push, fp16 wire
dtype, parallel pulls, wire accounting.

Reference contracts: the gRPC layer serializes tensors as a small header +
raw bytes (operators/detail/sendrecvop_utils.cc); ParameterServer2's sparse
parameter formats and the SelectedRows send path make gradient traffic
O(touched rows) (pserver/ParameterServer2.h, framework/selected_rows.h);
optimizer sparse branches update only touched rows
(operators/adam_op.h SparseAdamFunctor).
"""

import socket
import threading
import time

import numpy as np
import pytest

from paddle_tpu.core import flags
from paddle_tpu.distributed import (ParameterServer, ParamClient, serve,
                                    RpcClient, SparseGrad, send_msg,
                                    recv_msg)


def _start_ps(**kw):
    ps, rpc = serve(**kw)
    rpc.serve_in_thread()
    return ps, rpc


def _roundtrip(obj, wire):
    """Send obj through a socketpair with the given codec; return the
    decoded object (reader on a thread so large payloads can't deadlock
    the kernel socket buffer)."""
    a, b = socket.socketpair()
    out = {}

    def read():
        out["msg"] = recv_msg(b)

    t = threading.Thread(target=read)
    t.start()
    sent = send_msg(a, obj, wire=wire)
    t.join(10.0)
    a.close()
    b.close()
    got, nbytes, got_wire = out["msg"]
    assert nbytes == sent
    assert got_wire == wire
    return got


def _assert_payload_equal(x, y):
    if isinstance(x, np.ndarray):
        assert isinstance(y, np.ndarray)
        assert x.dtype == y.dtype and x.shape == y.shape
        np.testing.assert_array_equal(x, y)
    elif isinstance(x, SparseGrad):
        assert isinstance(y, SparseGrad)
        assert x.nrows == y.nrows and x.merged == y.merged
        _assert_payload_equal(x.rows, y.rows)
        _assert_payload_equal(x.values, y.values)
    elif isinstance(x, dict):
        assert set(x) == set(y)
        for k in x:
            _assert_payload_equal(x[k], y[k])
    elif isinstance(x, (list, tuple)):
        assert type(x) is type(y) and len(x) == len(y)
        for xi, yi in zip(x, y):
            _assert_payload_equal(xi, yi)
    else:
        assert x == y and type(x) is type(y)


# ---------------------------------------------------------------------------
# codec round-trip (the forward-compat guard: both wires carry identical
# payloads, so a framed client can always fall back to the pickle codec)
# ---------------------------------------------------------------------------

def test_framed_and_pickled_codecs_roundtrip_identical_payloads():
    payload = (
        "push",
        {
            "grads": {
                "w": np.arange(24, dtype=np.float32).reshape(4, 6),
                "half": np.ones((3, 2), np.float16),
                "ids": np.array([5, 1, 5], np.int64),
                "scalar0d": np.asarray(np.float32(2.5)),
                "empty": np.empty((0, 4), np.float32),
                "noncontig": np.arange(10, dtype=np.float64)[::2],
                "emb": SparseGrad(np.array([3, 1, 3], np.int64),
                                  np.ones((3, 2), np.float32), nrows=7),
            },
            "trainer_id": 3,
            "seq": 9,
            "note": "control strings ride the skeleton",
            "nested": [1, (2.5, None), {"deep": np.full((2,), 7, np.int32)}],
        },
    )
    framed = _roundtrip(payload, "framed")
    pickled = _roundtrip(payload, "pickle")
    _assert_payload_equal(framed, payload)
    _assert_payload_equal(pickled, payload)
    _assert_payload_equal(framed, pickled)


def test_framed_wire_is_smaller_than_pickle_for_tensors_and_counts_bytes():
    big = {"w": np.ones((64, 1024), np.float32)}
    a, b = socket.socketpair()
    out = {}

    def read():
        out["m"] = recv_msg(b)

    for wire in ("framed", "pickle"):
        t = threading.Thread(target=read)
        t.start()
        sent = send_msg(a, big, wire=wire)
        t.join(10.0)
        out[wire] = sent
    a.close()
    b.close()
    # framing overhead over the raw 256 KiB of tensor bytes is tiny
    assert out["framed"] < big["w"].nbytes + 512
    assert out["framed"] <= out["pickle"]


def test_server_answers_in_the_request_codec():
    ps, rpc = _start_ps(optimizer="sgd", opt_kwargs={"lr": 1.0},
                        mode="async")
    framed = ParamClient([rpc.address], trainer_id=0)
    legacy = ParamClient([rpc.address], trainer_id=1, param_names=["w"],
                         wire="pickle")
    framed.init_params({"w": np.zeros(4, np.float32)})
    framed.push({"w": np.ones(4, np.float32)})
    legacy.push({"w": np.ones(4, np.float32)})
    np.testing.assert_array_equal(legacy.pull()["w"],
                                  -2.0 * np.ones(4, np.float32))
    np.testing.assert_array_equal(framed.pull()["w"], legacy.pull()["w"])
    framed.close()
    legacy.close()
    rpc.shutdown()


# ---------------------------------------------------------------------------
# sparse push: O(touched rows) wire + rowwise server-side apply
# ---------------------------------------------------------------------------

def test_sparse_push_matches_dense_sgd():
    """A SparseGrad push (with duplicate ids the server must merge) lands
    exactly like the equivalent dense gradient."""
    table0 = np.random.RandomState(0).normal(
        size=(10, 4)).astype(np.float32)
    rows = np.array([3, 1, 3], np.int64)           # 3 twice: MergeAdd
    vals = np.array([[1, 1, 1, 1], [2, 2, 2, 2], [4, 4, 4, 4]], np.float32)

    dense = np.zeros_like(table0)
    np.add.at(dense, rows, vals)

    ps_d = ParameterServer(optimizer="sgd", opt_kwargs={"lr": 0.1})
    ps_d.init_params({"emb": table0})
    ps_d.push({"emb": dense}, trainer_id=0, seq=1)

    ps_s = ParameterServer(optimizer="sgd", opt_kwargs={"lr": 0.1})
    ps_s.init_params({"emb": table0})
    ps_s.push({"emb": SparseGrad(rows, vals, nrows=10)}, trainer_id=0,
              seq=1)

    np.testing.assert_allclose(ps_s.pull()["emb"], ps_d.pull()["emb"],
                               rtol=1e-6)
    # untouched rows are bitwise untouched
    untouched = [i for i in range(10) if i not in (1, 3)]
    np.testing.assert_array_equal(ps_s.pull()["emb"][untouched],
                                  table0[untouched])


def test_sparse_push_rowwise_adam_state_and_laziness():
    """Rowwise Adam: m1/m2/t update only for touched rows (per-row t —
    lazy bias correction), untouched rows keep zero state and do not
    move."""
    table0 = np.ones((6, 3), np.float32)
    ps = ParameterServer(optimizer="adam", opt_kwargs={"lr": 0.01})
    ps.init_params({"emb": table0})
    g = SparseGrad(np.array([0, 2], np.int64),
                   np.ones((2, 3), np.float32), nrows=6)
    ps.push({"emb": g}, trainer_id=0, seq=1)
    ps.push({"emb": g}, trainer_id=0, seq=2)
    st = ps._opt_state["emb"]
    np.testing.assert_array_equal(st["t"], [2, 0, 2, 0, 0, 0])
    assert st["m1"][[1, 3, 4, 5]].sum() == 0.0
    assert np.abs(st["m1"][[0, 2]]).min() > 0
    w = ps.pull()["emb"]
    np.testing.assert_array_equal(w[[1, 3, 4, 5]], table0[[1, 3, 4, 5]])
    assert np.abs(w[[0, 2]] - table0[[0, 2]]).min() > 1e-4


def test_sparse_rowwise_state_checkpoints_bitwise(tmp_path):
    """Rowwise m1/m2/t persist and restore bitwise, and the restored
    server continues bit-identically through further sparse pushes (the
    PR-2 checkpoint invariant extended to sparse state)."""
    path = str(tmp_path / "ps.ckpt")
    rng = np.random.RandomState(1)
    ps = ParameterServer(optimizer="adam", opt_kwargs={"lr": 0.01},
                         mode="async")
    ps.init_params({"emb": rng.normal(size=(8, 3)).astype(np.float32)})
    for s in range(1, 4):
        g = SparseGrad(rng.randint(0, 8, size=(4,)),
                       rng.normal(size=(4, 3)).astype(np.float32), nrows=8)
        ps.push({"emb": g}, trainer_id=1, seq=s)
    ps.save_checkpoint(path)

    ps2 = ParameterServer(optimizer="adam", opt_kwargs={"lr": 0.01},
                          mode="async")
    assert ps2.restore(path) is True
    for k in ("m1", "m2", "t"):
        np.testing.assert_array_equal(ps._opt_state["emb"][k],
                                      ps2._opt_state["emb"][k])
    # replayed pre-crash sparse push: answered from dedup, not re-applied
    before = np.array(ps2.pull()["emb"], copy=True)
    ps2.push({"emb": SparseGrad(np.array([0]), np.ones((1, 3), np.float32),
                                nrows=8)}, trainer_id=1, seq=3)
    np.testing.assert_array_equal(ps2.pull()["emb"], before)
    # the next fresh sparse push continues bit-identically on both
    g4 = SparseGrad(np.array([2, 5]),
                    rng.normal(size=(2, 3)).astype(np.float32), nrows=8)
    ps.push({"emb": g4}, trainer_id=1, seq=4)
    ps2.push({"emb": g4}, trainer_id=1, seq=4)
    np.testing.assert_array_equal(ps.pull()["emb"], ps2.pull()["emb"])


def test_sync_round_merges_sparse_pushes_across_trainers():
    """fan_in=2 sync round of two SparseGrads (overlapping rows): the
    applied update is the averaged merged gradient, like the dense
    barrier contract."""
    table0 = np.zeros((5, 2), np.float32)
    ps = ParameterServer(optimizer="sgd", opt_kwargs={"lr": 1.0},
                         mode="sync", fan_in=2)
    ps.init_params({"emb": table0})
    g1 = SparseGrad(np.array([0, 2]), np.ones((2, 2), np.float32), nrows=5)
    g2 = SparseGrad(np.array([2, 4]),
                    2 * np.ones((2, 2), np.float32), nrows=5)

    t = threading.Thread(target=lambda: ps.push({"emb": g1}, trainer_id=0,
                                                seq=1))
    t.start()
    ps.push({"emb": g2}, trainer_id=1, seq=1)
    t.join()
    expect = np.zeros((5, 2), np.float32)
    expect[0] -= 1.0 / 2
    expect[2] -= (1.0 + 2.0) / 2
    expect[4] -= 2.0 / 2
    np.testing.assert_allclose(ps.pull()["emb"], expect, rtol=1e-6)


def test_sync_round_mixing_dense_and_sparse_for_one_param():
    ps = ParameterServer(optimizer="sgd", opt_kwargs={"lr": 1.0},
                         mode="sync", fan_in=2)
    ps.init_params({"emb": np.zeros((4, 2), np.float32)})
    sparse = SparseGrad(np.array([1]), np.ones((1, 2), np.float32), nrows=4)
    dense = np.full((4, 2), 2.0, np.float32)

    t = threading.Thread(target=lambda: ps.push({"emb": sparse},
                                                trainer_id=0, seq=1))
    t.start()
    ps.push({"emb": dense}, trainer_id=1, seq=1)
    t.join()
    expect = -(dense + SparseGrad(np.array([1]),
                                  np.ones((1, 2), np.float32),
                                  nrows=4).to_dense()) / 2
    np.testing.assert_allclose(ps.pull()["emb"], expect, rtol=1e-6)


def test_param_client_converts_core_sparse_rows():
    """A trainer pushing the executor's own SparseRows (jax arrays,
    sentinel padding == nrows) ships only the real touched rows and the
    server result matches the densified gradient."""
    jnp = pytest.importorskip("jax.numpy")
    from paddle_tpu.core.sparse import SparseRows

    ps, rpc = _start_ps(optimizer="sgd", opt_kwargs={"lr": 1.0},
                        mode="async")
    c = ParamClient([rpc.address], trainer_id=0)
    nrows, dim = 512, 8
    table0 = np.zeros((nrows, dim), np.float32)
    c.init_params({"emb": table0})
    # 5 entries, two of them sentinel padding (row 512 == nrows)
    sr = SparseRows(jnp.asarray([1, 4, 1, nrows, nrows], jnp.int32),
                    jnp.ones((5, dim), jnp.float32), nrows=nrows)
    sent0 = c.wire_stats()["bytes_sent"]
    c.push({"emb": sr})
    pushed_bytes = c.wire_stats()["bytes_sent"] - sent0
    # wire carries the 3 real rows + a small header — far below the dense
    # 16 KiB table gradient
    assert pushed_bytes < 2000 < table0.nbytes
    expect = np.zeros((nrows, dim), np.float32)
    expect[1] -= 2.0   # row 1 twice, merged
    expect[4] -= 1.0
    np.testing.assert_allclose(c.pull()["emb"], expect, rtol=1e-6)
    c.close()
    rpc.shutdown()


def test_sparse_push_bytes_scale_with_touched_rows():
    ps, rpc = _start_ps(optimizer="sgd", mode="async")
    c = ParamClient([rpc.address], trainer_id=0)
    dim, nrows = 16, 4096
    c.init_params({"emb": np.zeros((nrows, dim), np.float32)})

    def push_bytes(k):
        g = SparseGrad(np.arange(k, dtype=np.int64),
                       np.ones((k, dim), np.float32), nrows=nrows,
                       merged=True)
        before = c.wire_stats()["bytes_sent"]
        c.push({"emb": g})
        return c.wire_stats()["bytes_sent"] - before

    b64, b512 = push_bytes(64), push_bytes(512)
    # bytes grow ~8x for 8x the rows (headers amortize), and both are far
    # below the dense table push
    assert 6.0 < b512 / b64 < 9.0
    assert b512 < nrows * dim * 4 / 2
    c.close()
    rpc.shutdown()


def test_marked_param_sparsifies_densified_grads_on_the_wire():
    """A param in sparse_param_names (the transpiler's is_sparse marking)
    whose backward handed the trainer a DENSE grad still ships only its
    touched rows."""
    nrows, dim = 1024, 16
    ps, rpc = _start_ps(optimizer="sgd", opt_kwargs={"lr": 1.0},
                        mode="async")
    c = ParamClient([rpc.address], trainer_id=0,
                    sparse_param_names=["emb"])
    c.init_params({"emb": np.zeros((nrows, dim), np.float32)})
    dense = np.zeros((nrows, dim), np.float32)
    dense[3] = 1.0
    dense[700] = 2.0
    before = c.wire_stats()["bytes_sent"]
    c.push({"emb": dense})
    pushed = c.wire_stats()["bytes_sent"] - before
    assert pushed < 2000 < dense.nbytes          # 2 rows, not the table
    np.testing.assert_allclose(c.pull()["emb"], -dense, rtol=1e-6)
    # an UNmarked param with the same grad ships dense (no scan, no
    # behavior change)
    assert isinstance(c._wire_grad("other", dense), np.ndarray)
    # a mostly-dense grad for a marked param stays dense too
    assert isinstance(c._wire_grad("emb", np.ones((4, 2), np.float32)),
                      np.ndarray)
    c.close()
    rpc.shutdown()


def test_pull_copies_only_params_with_sparse_history():
    """Dense-only params pull by reference (dense rules rebind, so the
    handed-out array is immutable); a param's first rowwise apply
    copy-on-writes it and marks it copied-on-pull thereafter."""
    ps = ParameterServer(optimizer="sgd", opt_kwargs={"lr": 1.0})
    ps.init_params({"w": np.zeros(4, np.float32),
                    "emb": np.zeros((4, 2), np.float32)})
    assert ps.pull()["w"] is ps._params["w"]          # no per-pull memcpy
    held = ps.pull()["emb"]                           # ref from dense era
    ps.push({"emb": SparseGrad(np.array([1]),
                               np.ones((1, 2), np.float32), nrows=4)},
            trainer_id=0, seq=1)
    # COW: the in-place apply ran on a fresh copy, not the held reference
    np.testing.assert_array_equal(held, np.zeros((4, 2), np.float32))
    got = ps.pull()["emb"]
    assert got is not ps._params["emb"]               # sparse params copy
    np.testing.assert_array_equal(got[1], [-1.0, -1.0])


# ---------------------------------------------------------------------------
# fp16 wire dtype
# ---------------------------------------------------------------------------

def test_fp16_wire_halves_push_bytes_and_accumulates_fp32():
    old = flags.get_flag("pserver_wire_dtype")
    ps, rpc = _start_ps(optimizer="sgd", opt_kwargs={"lr": 1.0},
                        mode="async")
    c = ParamClient([rpc.address], trainer_id=0)
    g = np.random.RandomState(0).normal(size=(256, 64)).astype(np.float32)
    c.init_params({"w": np.zeros_like(g)})
    try:
        before = c.wire_stats()["bytes_sent"]
        c.push({"w": g})
        fp32_bytes = c.wire_stats()["bytes_sent"] - before

        flags.set_flags({"pserver_wire_dtype": "fp16"})
        before = c.wire_stats()["bytes_sent"]
        c.push({"w": g})
        fp16_bytes = c.wire_stats()["bytes_sent"] - before
        assert fp16_bytes < 0.6 * fp32_bytes
        got = c.pull()["w"]
        # server params stay fp32; the applied value reflects the fp16
        # wire rounding of the SECOND push only
        assert got.dtype == np.float32
        np.testing.assert_allclose(
            got, -(g + g.astype(np.float16).astype(np.float32)),
            rtol=1e-6)
    finally:
        flags.set_flags({"pserver_wire_dtype": old})
        c.close()
        rpc.shutdown()


def test_fp16_wire_applies_to_sparse_values():
    old = flags.get_flag("pserver_wire_dtype")
    ps = ParameterServer(optimizer="sgd", opt_kwargs={"lr": 1.0})
    ps.init_params({"emb": np.zeros((4, 2), np.float32)})
    try:
        flags.set_flags({"pserver_wire_dtype": "fp16"})
        sg = ParamClient([("127.0.0.1", 1)])._wire_grad(
            "emb", SparseGrad(np.array([1]),
                              np.full((1, 2), 0.1, np.float32), nrows=4))
        assert sg.values.dtype == np.float16
        ps.push({"emb": sg}, trainer_id=0, seq=1)
        assert ps.pull()["emb"].dtype == np.float32
        np.testing.assert_allclose(
            ps.pull()["emb"][1],
            -np.full((2,), 0.1, np.float16).astype(np.float32))
    finally:
        flags.set_flags({"pserver_wire_dtype": old})


# ---------------------------------------------------------------------------
# parallel pull + error aggregation (the push contract, now on pull)
# ---------------------------------------------------------------------------

def test_pull_fans_out_and_aggregates_all_shard_errors():
    ps1, rpc1 = _start_ps(optimizer="sgd")
    ps2, rpc2 = _start_ps(optimizer="sgd")
    c = ParamClient([rpc1.address, rpc2.address], trainer_id=1)
    params = {f"p{i}": np.full((2,), float(i), np.float32)
              for i in range(4)}
    c.init_params(params)
    got = c.pull()
    for i in range(4):
        np.testing.assert_array_equal(got[f"p{i}"], params[f"p{i}"])
    rpc1.kill()
    rpc2.kill()
    with pytest.raises(RuntimeError) as ei:
        c.pull()
    msg = str(ei.value)
    assert "shard 0" in msg and "shard 1" in msg, msg
    c.close()


def test_pull_single_shard_error_keeps_original_type():
    ps1, rpc1 = _start_ps(optimizer="sgd")
    ps2, rpc2 = _start_ps(optimizer="sgd")
    c = ParamClient([rpc1.address, rpc2.address], trainer_id=1)
    c.init_params({f"p{i}": np.zeros(2, np.float32) for i in range(4)})
    rpc2.kill()
    with pytest.raises((EOFError, ConnectionError, OSError)):
        c.pull()
    c.close()
    rpc1.shutdown()


def test_pull_runs_shards_concurrently():
    """A slow shard must overlap with the fast one — pull wall time is
    max(shards), not sum (the satellite's whole point)."""
    from paddle_tpu.distributed import FaultPlan

    delay = 0.4
    plan1 = FaultPlan().delay("pull", 0, delay)
    plan2 = FaultPlan().delay("pull", 0, delay)
    ps1, rpc1 = _start_ps(optimizer="sgd", fault_plan=plan1)
    ps2, rpc2 = _start_ps(optimizer="sgd", fault_plan=plan2)
    c = ParamClient([rpc1.address, rpc2.address], trainer_id=1)
    c.init_params({f"p{i}": np.zeros(2, np.float32) for i in range(4)})
    t0 = time.monotonic()
    c.pull()
    dt = time.monotonic() - t0
    assert dt < 2 * delay * 0.95, f"pull took {dt:.3f}s — sequential?"
    c.close()
    rpc1.shutdown()
    rpc2.shutdown()


# ---------------------------------------------------------------------------
# sync fan-in accumulation owns its buffer (satellite)
# ---------------------------------------------------------------------------

def test_sync_fan_in_accumulation_does_not_mutate_caller_arrays():
    ps = ParameterServer(optimizer="sgd", opt_kwargs={"lr": 1.0},
                         mode="sync", fan_in=2)
    ps.init_params({"w": np.zeros(3, np.float32)})
    g = np.ones(3, np.float32)          # SAME array pushed by both

    t = threading.Thread(target=lambda: ps.push({"w": g}, trainer_id=0,
                                                seq=1))
    t.start()
    ps.push({"w": g}, trainer_id=1, seq=1)
    t.join()
    np.testing.assert_array_equal(g, np.ones(3, np.float32))
    np.testing.assert_array_equal(ps.pull()["w"], -np.ones(3, np.float32))


# ---------------------------------------------------------------------------
# rpc_timeout_s flag threading (satellite)
# ---------------------------------------------------------------------------

def test_rpc_timeout_flag_threads_through_clients():
    old = flags.get_flag("rpc_timeout_s")
    try:
        flags.set_flags({"rpc_timeout_s": 0.5})
        assert RpcClient(("127.0.0.1", 1))._timeout == 0.5
        pc = ParamClient([("127.0.0.1", 1)])
        assert all(c._timeout == 0.5 for c in pc._clients)
        # explicit override still wins
        assert RpcClient(("127.0.0.1", 1), timeout=2.0)._timeout == 2.0
    finally:
        flags.set_flags({"rpc_timeout_s": old})


def test_rpc_timeout_flag_bounds_a_stuck_call():
    class Stuck:
        def hang(self):
            time.sleep(5.0)

    from paddle_tpu.distributed import RpcServer

    srv = RpcServer(Stuck())
    srv.serve_in_thread()
    old = flags.get_flag("rpc_timeout_s")
    try:
        flags.set_flags({"rpc_timeout_s": 0.3})
        c = RpcClient(srv.address)
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            c.call("hang")
        assert time.monotonic() - t0 < 2.0
        c.close()
    finally:
        flags.set_flags({"rpc_timeout_s": old})
        srv.shutdown()


def test_supervisor_heartbeat_timeout_follows_flag():
    from paddle_tpu.distributed import PserverSupervisor

    old = flags.get_flag("rpc_timeout_s")
    try:
        flags.set_flags({"rpc_timeout_s": 0.75})
        sup = PserverSupervisor(n_servers=1)
        try:
            assert sup._hb_timeout == 0.75
        finally:
            sup.stop()
    finally:
        flags.set_flags({"rpc_timeout_s": old})


# ---------------------------------------------------------------------------
# wire accounting surfaces
# ---------------------------------------------------------------------------

def test_wire_counters_surface_in_server_stats_and_client():
    ps, rpc = _start_ps(optimizer="sgd", mode="async")
    c = ParamClient([rpc.address], trainer_id=0)
    c.init_params({"w": np.zeros((32, 8), np.float32)})
    c.push({"w": np.ones((32, 8), np.float32)})
    c.pull()
    # the server notes a call AFTER sending its response (bytes_sent is
    # only known then), so the client can observably return a beat
    # before the server's accounting lands — poll it in
    deadline = time.monotonic() + 5.0
    st = ps.stats()
    while "pull" not in st["wire"]["calls"] \
            and time.monotonic() < deadline:
        time.sleep(0.01)
        st = ps.stats()
    assert st["wire"]["bytes_recv"] > 32 * 8 * 4         # saw the push
    assert st["wire"]["calls"]["push"]["count"] == 1
    assert st["wire"]["calls"]["pull"]["count"] == 1
    cs = c.wire_stats()
    assert cs["bytes_sent"] > 32 * 8 * 4
    assert cs["calls"]["pull"]["count"] == 1
    assert cs["calls"]["pull"]["total_s"] > 0
    c.close()
    rpc.shutdown()


def test_rpc_calls_record_profiler_spans():
    from paddle_tpu.core import profiler

    ps, rpc = _start_ps(optimizer="sgd", mode="async")
    c = ParamClient([rpc.address], trainer_id=0)
    profiler.enable_profiler()
    try:
        c.init_params({"w": np.zeros(4, np.float32)})
        c.push({"w": np.ones(4, np.float32)})
        rows = profiler.disable_profiler(sorted_key="total")
    finally:
        c.close()
        rpc.shutdown()
    names = {r["name"] for r in rows}
    assert "rpc.client/push" in names
    assert "rpc.client/init_params" in names
