"""fused_sgd / fused_momentum / fused_adam: one op, all dense params.

Contract (ops/optimizer_ops.py fused ops, ops/pallas/optimizer.py,
fluid.optimizer ``fused=True``): under kernel_tier=jnp the fused op
applies the per-param dense expressions verbatim — the training
trajectory is BITWISE the per-param program's; under kernel_tier=pallas
the whole dense update runs as one arena megakernel (interpret on CPU)
and matches to float tolerance. SparseRows grads (is_sparse embeddings)
ride the same fused op on its per-param branch.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework
from paddle_tpu.ops import pallas as tier


@pytest.fixture(autouse=True)
def _reset():
    yield
    fluid.set_flags({"kernel_tier": "auto"})
    tier.reset_fallback_counts()


def _make_optimizer(kind, fused):
    if kind == "sgd":
        return fluid.optimizer.SGD(learning_rate=0.05, fused=fused)
    if kind == "momentum":
        return fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                                        use_nesterov=True, fused=fused)
    return fluid.optimizer.Adam(learning_rate=0.01, fused=fused)


def _train(kind, fused, tier_name, steps=5, sparse_emb=False):
    fluid.set_flags({"kernel_tier": tier_name})
    framework.reset_unique_name()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 13
    with fluid.program_guard(main, startup):
        if sparse_emb:
            ids = fluid.layers.data("ids", shape=[1], dtype="int64",
                                    lod_level=1)
            emb = fluid.layers.embedding(ids, size=[12, 6], is_sparse=True)
            feat = fluid.layers.sequence_pool(emb, "sum")
        else:
            feat = fluid.layers.data("x", shape=[6])
        h = fluid.layers.fc(feat, size=10, act="relu")
        pred = fluid.layers.fc(h, size=1)
        label = fluid.layers.data("y", shape=[1])
        loss = fluid.layers.mean(fluid.layers.square(
            fluid.layers.elementwise_sub(pred, label)))
        _make_optimizer(kind, fused).minimize(loss, startup)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(1)
    if sparse_emb:
        # duplicate ids in one batch exercise the merge/scatter path
        seqs = [np.array([[1], [3], [3]], "int64"),
                np.array([[0], [7]], "int64"),
                np.array([[3]], "int64")]
        feed = {"ids": seqs,
                "y": rng.normal(0, 1, (3, 1)).astype("float32")}
    else:
        feed = {"x": rng.normal(0, 1, (4, 6)).astype("float32"),
                "y": rng.normal(0, 1, (4, 1)).astype("float32")}
    return [float(exe.run(main, feed=feed, fetch_list=[loss],
                          scope=scope)[0]) for _ in range(steps)]


@pytest.mark.parametrize("kind", ["sgd", "momentum", "adam"])
def test_fused_bitwise_under_jnp_tier(kind):
    base = _train(kind, fused=False, tier_name="jnp")
    fused = _train(kind, fused=True, tier_name="jnp")
    assert base == fused, (kind, base, fused)
    assert fused[-1] < fused[0]


@pytest.mark.parametrize("kind", ["sgd", "momentum", "adam"])
def test_fused_pallas_megakernel_matches(kind):
    base = _train(kind, fused=False, tier_name="jnp")
    pallas = _train(kind, fused=True, tier_name="pallas")
    np.testing.assert_allclose(pallas, base, rtol=5e-4, atol=1e-6)
    assert tier.fallback_counts().get("optimizer", 0) == 0


@pytest.mark.parametrize("kind", ["sgd", "momentum", "adam"])
def test_fused_with_sparse_embedding_grad(kind):
    """An is_sparse embedding's SparseRows grad takes the fused op's
    per-param branch while the dense params fuse — trajectory matches the
    per-param program on both tiers."""
    base = _train(kind, fused=False, tier_name="jnp", sparse_emb=True)
    fused = _train(kind, fused=True, tier_name="jnp", sparse_emb=True)
    assert base == fused, (kind, base, fused)
    pallas = _train(kind, fused=True, tier_name="pallas", sparse_emb=True)
    np.testing.assert_allclose(pallas, base, rtol=5e-4, atol=1e-6)


def test_fused_program_structure():
    framework.reset_unique_name()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        pred = fluid.layers.fc(fluid.layers.fc(x, size=8), size=1)
        label = fluid.layers.data("y", shape=[1])
        loss = fluid.layers.mean(fluid.layers.square(
            fluid.layers.elementwise_sub(pred, label)))
        fluid.optimizer.Adam(learning_rate=0.01, fused=True).minimize(
            loss, startup)
    types = [op.type for op in main.global_block().ops]
    assert types.count("fused_adam") == 1
    assert "adam" not in types
    fused = [op for op in main.global_block().ops
             if op.type == "fused_adam"][0]
    assert len(fused.input("Params")) == 4          # 2x (weight + bias)
    # ONE shared beta-power pair instead of per-param pairs
    assert len(fused.input("Beta1Pow")) == 1
    assert types.count("scale") == 2


def test_unfused_optimizer_has_no_fused_op():
    """fused=False (the default) keeps the per-param program — the
    DistributeTranspiler contract."""
    framework.reset_unique_name()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        pred = fluid.layers.fc(x, size=1)
        label = fluid.layers.data("y", shape=[1])
        loss = fluid.layers.mean(fluid.layers.square(
            fluid.layers.elementwise_sub(pred, label)))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss, startup)
    types = [op.type for op in main.global_block().ops]
    assert "fused_sgd" not in types and types.count("sgd") == 2


def test_fused_unsupported_optimizer_raises():
    framework.reset_unique_name()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        pred = fluid.layers.fc(x, size=1)
        label = fluid.layers.data("y", shape=[1])
        loss = fluid.layers.mean(fluid.layers.square(
            fluid.layers.elementwise_sub(pred, label)))
        with pytest.raises(NotImplementedError, match="fused"):
            fluid.optimizer.Adagrad(learning_rate=0.1, fused=True).minimize(
                loss, startup)
