"""OpTest — per-op numeric testing harness.

Port of the reference's contract (/root/reference/python/paddle/fluid/tests/
unittests/op_test.py:212): a test defines ``op_type``, ``inputs``, ``attrs``
and numpy-computed ``outputs``; ``check_output`` builds a single-op program
and compares executor results against the numpy reference on both the eager
interpreter and the jit-compiled path (the reference's CPU/CUDA place pair →
our eager/jit pair). ``check_grad`` compares analytic gradients obtained by
``append_backward`` against central finite differences
(reference op_test.py:97 get_numeric_gradient, :378 check_grad).

LoD inputs are passed as ``(np_array, lod)`` tuples exactly like the
reference (op_test.py:465).
"""

from __future__ import annotations

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.core.lod import LoDArray, lodarray_to_flat, flat_to_lodarray


def _as_np(v):
    if isinstance(v, tuple):
        return np.asarray(v[0])
    return np.asarray(v)


class OpTest:
    """Subclass-style harness; pytest test classes inherit and call
    check_output/check_grad from test methods."""

    op_type: str = None
    inputs: dict = {}
    outputs: dict = {}
    attrs: dict = {}

    # -- program construction ------------------------------------------------
    def _build(self, extra_loss=False):
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            block = main.global_block()
            in_slots, feed = {}, {}
            for slot, value in self.inputs.items():
                entries = value if isinstance(value, list) else [(slot, value)]
                names = []
                for sub_name, sub_val in entries:
                    lod_level = 1 if isinstance(sub_val, tuple) else 0
                    arr = _as_np(sub_val)
                    block.create_var(name=sub_name, shape=arr.shape,
                                     dtype=str(arr.dtype), lod_level=lod_level,
                                     stop_gradient=False, is_data=True)
                    feed[sub_name] = sub_val if lod_level else arr
                    names.append(sub_name)
                in_slots[slot] = names
            out_slots = {}
            for slot, value in self.outputs.items():
                entries = value if isinstance(value, list) else [(slot, value)]
                names = []
                for sub_name, sub_val in entries:
                    lod_level = 1 if isinstance(sub_val, tuple) else 0
                    block.create_var(name=sub_name, lod_level=lod_level)
                    names.append(sub_name)
                out_slots[slot] = names
            block.append_op(self.op_type, in_slots, out_slots, dict(self.attrs))
        # unconditional verify (not flag-gated): every OpTest program runs
        # through the structural verifier, so a test declaring slots that
        # disagree with the op's registered SlotSpec fails with a PTL
        # diagnostic instead of a KeyError inside the lowering
        from paddle_tpu.fluid.analysis import verify_program
        verify_program(main, feed_names=list(feed))
        return main, startup, feed

    def _out_entries(self):
        for slot, value in self.outputs.items():
            entries = value if isinstance(value, list) else [(slot, value)]
            for sub_name, sub_val in entries:
                yield slot, sub_name, sub_val

    # -- forward check -------------------------------------------------------
    def check_output(self, atol=1e-5, rtol=1e-4, no_check_set=None):
        for mode in ("eager", "jit"):
            main, startup, feed = self._build()
            exe = fluid.Executor(fluid.CPUPlace(), mode=mode)
            fetch_names = [n for _, n, _ in self._out_entries()]
            results = exe.run(main, feed=feed, fetch_list=fetch_names)
            for (slot, name, expect), got in zip(self._out_entries(), results):
                if isinstance(got, LoDArray):
                    got_flat, got_lod = lodarray_to_flat(got)
                    if isinstance(expect, tuple):
                        np.testing.assert_allclose(
                            got_flat, np.asarray(expect[0]), atol=atol,
                            rtol=rtol, err_msg=f"[{mode}] output {name} (lod)")
                        assert got_lod[0] == list(np.asarray(expect[1][0])), \
                            f"[{mode}] lod mismatch for {name}"
                        continue
                    got = got_flat
                np.testing.assert_allclose(
                    np.asarray(got, dtype=np.float64),
                    np.asarray(_as_np(expect), dtype=np.float64).reshape(
                        np.asarray(got).shape),
                    atol=atol, rtol=rtol, err_msg=f"[{mode}] output {name}")

    # -- gradient check ------------------------------------------------------
    def _loss_value(self, outs, output_names):
        return sum(float(np.mean(np.asarray(o, dtype=np.float64)))
                   for n, o in outs.items() if n in output_names)

    def _forward_loss(self, exe, main, feed, output_names):
        results = exe.run(main, feed=feed, fetch_list=list(output_names))
        vals = {}
        for n, r in zip(output_names, results):
            if isinstance(r, LoDArray):
                r, _ = lodarray_to_flat(r)
            vals[n] = r
        return self._loss_value(vals, output_names)

    def check_grad(self, inputs_to_check, output_names,
                   max_relative_error=0.005, no_grad_set=None,
                   numeric_grad_delta=0.005, user_defined_grads=None):
        if isinstance(output_names, str):
            output_names = [output_names]

        # ---- analytic grads via append_backward ----
        main, startup, feed = self._build()
        with fluid.program_guard(main, startup):
            block = main.global_block()
            means = []
            for n in output_names:
                m = fluid.layers.mean(block.var(n))
                means.append(m)
            loss = means[0]
            for m in means[1:]:
                loss = loss + m
            fluid.append_backward(loss)
        exe = fluid.Executor(fluid.CPUPlace(), mode="jit")
        grad_names = [fluid.grad_var_name(n) for n in inputs_to_check]
        analytic = exe.run(main, feed=feed, fetch_list=grad_names)
        analytic = [lodarray_to_flat(a)[0] if isinstance(a, LoDArray)
                    else np.asarray(a) for a in analytic]

        # ---- numeric grads by central differences ----
        if user_defined_grads is not None:
            numeric = [np.asarray(g) for g in user_defined_grads]
        else:
            main_f, _, feed_f = self._build()
            exe_f = fluid.Executor(fluid.CPUPlace(), mode="jit")
            numeric = []
            for name in inputs_to_check:
                base = feed_f[name]
                if isinstance(base, tuple):
                    arr = np.asarray(base[0]).copy()
                    lod = base[1]
                    rebuild = lambda a: (a, lod)
                else:
                    arr = np.asarray(base).copy()
                    rebuild = lambda a: a
                grad = np.zeros_like(arr, dtype=np.float64)
                flat = arr.reshape(-1)
                for i in range(flat.size):
                    orig = flat[i]
                    flat[i] = orig + numeric_grad_delta
                    feed_f[name] = rebuild(arr)
                    lp = self._forward_loss(exe_f, main_f, feed_f, output_names)
                    flat[i] = orig - numeric_grad_delta
                    feed_f[name] = rebuild(arr)
                    lm = self._forward_loss(exe_f, main_f, feed_f, output_names)
                    flat[i] = orig
                    grad.reshape(-1)[i] = (lp - lm) / (2 * numeric_grad_delta)
                feed_f[name] = rebuild(arr)
                numeric.append(grad)

        # ---- compare (reference op_test.py __assert_is_close) ----
        for name, a, n in zip(inputs_to_check, analytic, numeric):
            a = np.asarray(a, dtype=np.float64).reshape(n.shape)
            abs_a = np.maximum(np.abs(a), 1e-3)
            diff = np.abs(a - n) / abs_a
            max_diff = diff.max() if diff.size else 0.0
            assert max_diff <= max_relative_error, (
                f"gradient mismatch for input {name}: max relative error "
                f"{max_diff:.6f} > {max_relative_error} "
                f"(analytic={a.reshape(-1)[:5]}, numeric={n.reshape(-1)[:5]})")
