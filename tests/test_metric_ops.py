"""Metric op + Evaluator tests vs numpy/sklearn-style references.

Reference OpTests: test_auc_op.py, test_precision_recall_op.py,
test_chunk_eval_op.py (python/paddle/fluid/tests/unittests/);
evaluators per python/paddle/fluid/evaluator.py:42-254.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.ops.metrics import extract_chunks

layers = fluid.layers


def _run(builder, feed, mode="jit"):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetch = builder()
    exe = fluid.Executor(fluid.CPUPlace(), mode=mode)
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    return exe.run(main, feed=feed, fetch_list=list(fetch), scope=scope)


def _auc_np(scores, labels, num_thresholds, curve="ROC"):
    eps = 1e-7
    ths = [0.0 - eps] + [i / (num_thresholds - 1)
                         for i in range(1, num_thresholds - 1)] + [1.0 + eps]
    xs, ys = [], []
    for t in ths:
        tp = ((scores >= t) & (labels > 0)).sum()
        fn = ((scores < t) & (labels > 0)).sum()
        fp = ((scores >= t) & (labels == 0)).sum()
        tn = ((scores < t) & (labels == 0)).sum()
        if curve == "ROC":
            xs.append(fp / max(fp + tn, 1e-12))
            ys.append(tp / max(tp + fn, 1e-12))
        else:
            xs.append(tp / max(tp + fn, 1e-12))
            ys.append(tp / max(tp + fp, 1e-12))
    a = 0.0
    for i in range(len(ths) - 1):
        a += (xs[i] - xs[i + 1]) * (ys[i] + ys[i + 1]) / 2
    return a


@pytest.mark.parametrize("curve", ["ROC", "PR"])
def test_auc_matches_numpy(curve):
    rng = np.random.RandomState(0)
    n = 200
    labels = rng.randint(0, 2, (n, 1)).astype("int64")
    # informative scores: positives skew high
    scores = np.clip(0.5 * labels[:, 0] + rng.rand(n) * 0.7, 0, 1) \
        .astype("float32").reshape(n, 1)

    def build():
        p = layers.data("p", shape=[1])
        l = layers.data("l", shape=[1], dtype="int64")
        a, stats = layers.auc(p, l, curve=curve, num_thresholds=50)
        return [a]

    got, = _run(build, {"p": scores, "l": labels})
    exp = _auc_np(scores[:, 0], labels[:, 0], 50, curve)
    np.testing.assert_allclose(float(got), exp, rtol=1e-4, atol=1e-5)
    if curve == "ROC":
        assert float(got) > 0.7  # informative scores -> meaningful AUC


def test_precision_recall_matches_numpy():
    rng = np.random.RandomState(1)
    C, n = 4, 120
    labels = rng.randint(0, C, (n, 1)).astype("int64")
    preds = labels.copy()
    flip = rng.rand(n) < 0.3
    preds[flip] = rng.randint(0, C, (flip.sum(), 1))

    def build():
        i = layers.data("i", shape=[1], dtype="int64")
        l = layers.data("l", shape=[1], dtype="int64")
        batch, accum, states = layers.precision_recall(i, l, class_number=C)
        return [batch, states]

    batch, states = _run(build, {"i": preds, "l": labels})
    # numpy reference
    exp_states = np.zeros((C, 4))
    for c in range(C):
        p = preds[:, 0] == c
        t = labels[:, 0] == c
        exp_states[c] = [(p & t).sum(), (p & ~t).sum(),
                         (~p & ~t).sum(), (~p & t).sum()]
    np.testing.assert_allclose(states, exp_states)
    precs = [exp_states[c, 0] / max(exp_states[c, 0] + exp_states[c, 1], 1)
             if exp_states[c, 0] + exp_states[c, 1] > 0 else 1.0
             for c in range(C)]
    recs = [exp_states[c, 0] / max(exp_states[c, 0] + exp_states[c, 3], 1)
            if exp_states[c, 0] + exp_states[c, 3] > 0 else 1.0
            for c in range(C)]
    np.testing.assert_allclose(batch[0], np.mean(precs), rtol=1e-5)
    np.testing.assert_allclose(batch[1], np.mean(recs), rtol=1e-5)
    # micro: total TP over totals
    tps = exp_states[:, 0].sum()
    np.testing.assert_allclose(
        batch[3], tps / (tps + exp_states[:, 1].sum()), rtol=1e-5)


def test_extract_chunks_iob():
    # types: 0, 1; IOB tags: B0=0 I0=1 B1=2 I1=3, Outside=4
    tags = [0, 1, 1, 4, 2, 3, 0, 4]
    got = extract_chunks(tags, "IOB", 2)
    assert got == {(0, 2, 0), (4, 5, 1), (6, 6, 0)}


def test_extract_chunks_iobes():
    # IOBES: type*4 + {B:0 I:1 E:2 S:3}, Outside = 8
    tags = [0, 1, 2, 3, 8, 4, 6]
    got = extract_chunks(tags, "IOBES", 2)
    assert got == {(0, 2, 0), (3, 3, 0), (5, 6, 1)}


def test_chunk_eval_op():
    # two sequences, IOB over 2 types
    label_seqs = [[0, 1, 4, 2, 3], [0, 4, 2]]
    infer_seqs = [[0, 1, 4, 2, 4], [0, 4, 0]]

    def build():
        inf = layers.data("inf", shape=[1], dtype="int64", lod_level=1)
        lab = layers.data("lab", shape=[1], dtype="int64", lod_level=1)
        return layers.chunk_eval(inf, lab, chunk_scheme="IOB",
                                 num_chunk_types=2)[:3] + \
            layers.chunk_eval(inf, lab, chunk_scheme="IOB",
                              num_chunk_types=2)[3:]

    feed = {
        "inf": [np.array(s, "int64").reshape(-1, 1) for s in infer_seqs],
        "lab": [np.array(s, "int64").reshape(-1, 1) for s in label_seqs],
    }
    p, r, f1, ni, nl, nc = _run(build, feed, mode="eager")
    n_inf = sum(len(extract_chunks(s, "IOB", 2)) for s in infer_seqs)
    n_lab = sum(len(extract_chunks(s, "IOB", 2)) for s in label_seqs)
    n_cor = sum(len(extract_chunks(a, "IOB", 2)
                    & extract_chunks(b, "IOB", 2))
                for a, b in zip(infer_seqs, label_seqs))
    assert int(ni[0]) == n_inf and int(nl[0]) == n_lab
    assert int(nc[0]) == n_cor
    np.testing.assert_allclose(p[0], n_cor / n_inf, rtol=1e-5)
    np.testing.assert_allclose(r[0], n_cor / n_lab, rtol=1e-5)


def test_auc_evaluator_accumulates():
    """Stateful Auc evaluator over 4 batches equals single-shot AUC over
    the concatenation."""
    rng = np.random.RandomState(3)
    n = 400
    labels = rng.randint(0, 2, (n, 1)).astype("int64")
    scores = np.clip(0.55 * labels[:, 0] + rng.rand(n) * 0.6, 0, 1) \
        .astype("float32").reshape(n, 1)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        p = layers.data("p", shape=[1])
        l = layers.data("l", shape=[1], dtype="int64")
        ev = fluid.evaluator.Auc(p, l, num_thresholds=50)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    for i in range(0, n, 100):
        fetched = exe.run(main, feed={"p": scores[i:i + 100],
                                      "l": labels[i:i + 100]},
                          fetch_list=ev.metrics, scope=scope)
        ev.update(fetched)
    exp = _auc_np(scores[:, 0], labels[:, 0], 50)
    np.testing.assert_allclose(ev.eval(), exp, rtol=1e-4, atol=1e-5)


def test_accuracy_evaluator():
    rng = np.random.RandomState(4)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8])
        l = layers.data("l", shape=[1], dtype="int64")
        logits = layers.fc(x, size=3, act="softmax")
        ev = fluid.evaluator.Accuracy(logits, l)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    total_correct = total = 0
    for _ in range(3):
        xs = rng.normal(0, 1, (32, 8)).astype("float32")
        ls = rng.randint(0, 3, (32, 1)).astype("int64")
        fetched = exe.run(main, feed={"x": xs, "l": ls},
                          fetch_list=ev.metrics, scope=scope)
        ev.update(fetched)
        total_correct += int(np.asarray(fetched[0]))
        total += 32
    np.testing.assert_allclose(ev.eval(), total_correct / total)