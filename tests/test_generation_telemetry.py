"""Per-request generation lifecycle telemetry: TTFT/TPOT histograms on
the obs plane, the stamp-or-discard TTFT probe contract under abort
(the dangling-probe fix), and the flight-recorder lifecycle events
(admission -> chunked-prefill pumps -> finish/abort)."""

import time
import threading

import pytest

from paddle_tpu.obs import recorder as rec
from paddle_tpu.obs.metrics import REGISTRY, next_instance
from paddle_tpu.serving.generate.scheduler import ContinuousBatcher

_TTFT = REGISTRY.histogram("paddle_tpu_genengine_ttft_seconds",
                           labels=("instance",))
_TPOT = REGISTRY.histogram("paddle_tpu_genengine_tpot_seconds",
                           labels=("instance",))


class _Handle:
    def __init__(self):
        self.user_data = None
        self.finished = False


class _ScriptedEngine:
    """Deterministic ContinuousBatcher driver: start() admits instantly
    with NO first token (the beam / chunked-admission shape), step()
    pops pre-scripted events — so the abort-before-first-token race is
    a scripted certainty, not a timing accident."""

    def __init__(self):
        self.obs_instance = next_instance("fakegen")
        self.ttft = _TTFT.labels(instance=self.obs_instance)
        self.tpot = _TPOT.labels(instance=self.obs_instance)
        self._lock = threading.Lock()
        self._script = []
        self.handles = []
        self.aborted = []

    def start(self, prompt, max_new_tokens, sampling=None):
        h = _Handle()
        with self._lock:
            self.handles.append(h)
        return h, [], False

    def push_events(self, events):
        with self._lock:
            self._script.append(events)

    def step(self):
        with self._lock:
            if self._script:
                return self._script.pop(0)
        time.sleep(0.005)
        return []

    def abort(self, handle):
        handle.finished = True
        with self._lock:
            self.aborted.append(handle)


def _wait(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


# ---------------------------------------------------------------------------
# THE pin: stamp-or-discard on abort
# ---------------------------------------------------------------------------

def test_abort_before_first_token_discards_ttft_probe():
    """A stream aborted before its FIRST token must leave no dangling
    TTFT probe: the histogram sees no sample, the discard is counted,
    and the lifecycle closes with a gen_finish(ttft_discarded) event."""
    eng = _ScriptedEngine()
    b = ContinuousBatcher(eng, capacity=4)
    try:
        before = eng.ttft.count
        s = b.submit([1, 2, 3], 8, {"mode": "greedy"})
        assert _wait(lambda: eng.handles)         # admitted, zero tokens
        s.close()
        assert _wait(lambda: eng.aborted)
        assert _wait(lambda: b.stats()["ttft_discarded"] == 1)
        assert eng.ttft.count == before           # probe DISCARDED
        assert eng.tpot.count == 0
        with pytest.raises(Exception):
            list(s)                               # consumer sees cancel
        evs = rec.RECORDER.events(kinds={"gen_finish"})
        mine = [e for e in evs
                if e["component"] == b.obs_instance]
        assert mine and mine[-1]["detail"]["ttft_discarded"] is True
        assert mine[-1]["detail"]["tokens"] == 0
    finally:
        b.close()


def test_ttft_stamps_at_first_actual_token_and_tpot_on_finish():
    eng = _ScriptedEngine()
    b = ContinuousBatcher(eng, capacity=4)
    try:
        s = b.submit([1], 8, {"mode": "greedy"})
        assert _wait(lambda: eng.handles)
        h = eng.handles[0]
        assert _wait(lambda: h.user_data is not None)
        before_t, before_p = eng.ttft.count, eng.tpot.count
        # a tokenless heartbeat step must NOT stamp the probe
        eng.push_events([(h, [], False)])
        time.sleep(0.1)
        assert eng.ttft.count == before_t
        eng.push_events([(h, [7], False)])
        assert _wait(lambda: eng.ttft.count == before_t + 1)
        assert eng.tpot.count == before_p         # not until finish
        eng.push_events([(h, [8, 9], True)])
        toks = list(s)
        assert toks == [7, 8, 9]
        assert eng.ttft.count == before_t + 1     # stamped exactly once
        assert eng.tpot.count == before_p + 1     # once, >=2 tokens
        st = b.stats()
        assert st["ttft"]["count"] >= 1 and st["tpot"]["count"] >= 1
        assert st["ttft_discarded"] == 0
        evs = [e for e in rec.RECORDER.events(kinds={"gen_finish"})
               if e["component"] == b.obs_instance]
        assert evs[-1]["detail"]["reason"] == "finished"
        assert evs[-1]["detail"]["tokens"] == 3
        assert evs[-1]["detail"]["ttft_ms"] >= 0
    finally:
        b.close()


def test_abort_after_first_token_keeps_stamp_records_tpot():
    """The other half of stamp-or-discard: a stream cancelled AFTER
    tokens flowed keeps its TTFT sample (stamped at the token) and
    still resolves TPOT over what it emitted."""
    eng = _ScriptedEngine()
    b = ContinuousBatcher(eng, capacity=4)
    try:
        s = b.submit([1], 8, {"mode": "greedy"})
        assert _wait(lambda: eng.handles)
        h = eng.handles[0]
        assert _wait(lambda: h.user_data is not None)
        before_t, before_p = eng.ttft.count, eng.tpot.count
        eng.push_events([(h, [7, 8], False)])
        assert _wait(lambda: eng.ttft.count == before_t + 1)
        s.close()
        assert _wait(lambda: eng.aborted)
        assert _wait(lambda: eng.tpot.count == before_p + 1)
        assert eng.ttft.count == before_t + 1
        assert b.stats()["ttft_discarded"] == 0
    finally:
        b.close()


def test_worker_error_resolves_probes_typed():
    class _Dying(_ScriptedEngine):
        def step(self):
            raise RuntimeError("decode died")

    eng = _Dying()
    b = ContinuousBatcher(eng, capacity=4)
    try:
        before = eng.ttft.count
        s = b.submit([1], 8, {"mode": "greedy"})
        with pytest.raises(RuntimeError, match="decode died"):
            list(s)
        assert eng.ttft.count == before
        assert _wait(lambda: b.stats()["ttft_discarded"] >= 1)
    finally:
        b.close()


# ---------------------------------------------------------------------------
# real-engine integration: lifecycle events + histograms end to end
# ---------------------------------------------------------------------------

def test_real_engine_lifecycle_events_and_histograms(tmp_path):
    from paddle_tpu.serving.generate import GenerationEngine
    from paddle_tpu.testing.models import export_tiny_lm

    d = str(tmp_path / "lm")
    export_tiny_lm(d)
    eng = GenerationEngine(d, max_seqs=2, max_len=32, num_blocks=32,
                           block_size=4, prefill_buckets="8,16",
                           prefill_chunk=4)
    eng.warmup()
    b = ContinuousBatcher(eng)
    try:
        # a 12-token prompt under prefill_chunk=4 admits chunked: the
        # lifecycle is admission -> pump -> pump -> ... -> first token
        s = b.submit(list(range(1, 13)), 4, {"mode": "greedy"})
        toks = list(s)
        assert len(toks) == 4
        assert eng.ttft.count == 1 and eng.tpot.count == 1
        st = eng.stats()
        assert st["ttft"]["count"] == 1 and st["tpot"]["count"] == 1
        admits = [e for e in rec.RECORDER.events(kinds={"gen_admit"})
                  if e["component"] == eng.obs_instance]
        assert admits and admits[-1]["detail"]["chunked"] is True
        assert admits[-1]["detail"]["prompt_tokens"] == 12
        pumps = [e for e in
                 rec.RECORDER.events(kinds={"gen_prefill_chunk"})
                 if e["component"] == eng.obs_instance]
        # 12 tokens in 4-token chunks = 3 pumps, remaining counts down
        assert [p["detail"]["remaining"] for p in pumps] == [8, 4, 0]
        finishes = [e for e in rec.RECORDER.events(kinds={"gen_finish"})
                    if e["component"] == b.obs_instance]
        assert finishes[-1]["detail"]["tokens"] == 4

        # abort path on the real engine records gen_abort — close only
        # once the request is ADMITTED (a cancel still in the wait queue
        # never reached the engine, so there is nothing to abort)
        s2 = b.submit(list(range(1, 13)), 16, {"mode": "greedy"})
        assert _wait(lambda: eng.active_sequences > 0)
        s2.close()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            ab = [e for e in rec.RECORDER.events(kinds={"gen_abort"})
                  if e["component"] == eng.obs_instance]
            if ab:
                break
            time.sleep(0.02)
        assert ab, "abort left no gen_abort event"
    finally:
        b.close()
