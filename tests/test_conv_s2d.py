"""Space-to-depth stem conv rewrite: exactness + graph-level parity.

The flag `conv_space_to_depth` rewrites eligible stem convs (NHWC, stride 2,
C_in<=4 — the ResNet 7x7/s2 stem, reference benchmark/paddle/image/resnet.py
conv1) as a stride-1 conv over the 2x2 space-to-depth transform of the input.
The rewrite must be numerically exact (same summation graph up to float
reassociation) and invisible to checkpoints (filter stays OIHW 7x7).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core.flags import set_flags
from paddle_tpu.ops.conv_ops import _conv2d_compute, _s2d_stem_conv


@pytest.mark.parametrize("hw,c,o,k,p", [
    ((64, 64), 3, 16, 7, 3),   # the ResNet stem geometry (scaled down)
    ((32, 32), 3, 8, 5, 2),
    ((16, 20), 4, 8, 3, 1),
    ((32, 32), 1, 8, 7, 3),
])
def test_s2d_matches_direct_conv(hw, c, o, k, p):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.normal(size=(2, hw[0], hw[1], c)).astype("float32"))
    w = jnp.asarray(rng.normal(size=(o, c, k, k)).astype("float32"))
    ref = _conv2d_compute(x, w, (2, 2), (p, p), (1, 1), 1, "NHWC")
    y = _s2d_stem_conv(x, w, (p, p))
    assert y.shape == ref.shape
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


def test_s2d_flag_end_to_end_grad():
    """A conv+BN+pool slice trained one step with the flag on and off lands on
    the same loss and the same 7x7 filter gradient (the rewrite is inside the
    compiled step; the stored parameter keeps the reference OIHW shape)."""

    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = fluid.layers.data("img", shape=[32, 32, 3])
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            conv = fluid.layers.conv2d(img, num_filters=8, filter_size=7,
                                       stride=2, padding=3, act=None,
                                       bias_attr=False, data_format="NHWC")
            bn = fluid.layers.batch_norm(conv, act="relu", data_layout="NHWC")
            pool = fluid.layers.pool2d(bn, pool_size=4, pool_type="avg",
                                       global_pooling=True,
                                       data_format="NHWC")
            logits = fluid.layers.fc(pool, size=4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss, startup)
        return main, startup, loss

    rng = np.random.RandomState(1)
    feed = {"img": rng.normal(size=(4, 32, 32, 3)).astype("float32"),
            "label": rng.randint(0, 4, (4, 1)).astype("int64")}

    results = {}
    for flag in (False, True):
        set_flags({"conv_space_to_depth": flag})
        try:
            main, startup, loss = build()
            scope = fluid.Scope()
            exe = fluid.Executor()
            exe.run(startup, scope=scope)
            vals = []
            for _ in range(2):
                vals.append(exe.run(main, feed=feed, fetch_list=[loss],
                                    scope=scope)[0])
            results[flag] = np.asarray(vals)
        finally:
            set_flags({"conv_space_to_depth": False})
    np.testing.assert_allclose(results[False], results[True],
                               rtol=1e-4, atol=1e-5)


def test_conv_1x1_grad_as_dot_parity():
    """The conv_1x1_grad_as_dot A/B flag (1x1-conv grads as dot_general):
    training trajectories must be identical with it on and off."""

    def train_once(flag):
        set_flags({"conv_1x1_grad_as_dot": flag})
        try:
            main, startup = fluid.Program(), fluid.Program()
            main.random_seed = startup.random_seed = 7
            with fluid.program_guard(main, startup):
                img = fluid.layers.data("img", shape=[8, 8, 4])
                label = fluid.layers.data("label", shape=[1], dtype="int64")
                conv = fluid.layers.conv2d(img, num_filters=8, filter_size=1,
                                           act="relu", bias_attr=False,
                                           data_format="NHWC")
                pool = fluid.layers.pool2d(conv, pool_size=8,
                                           pool_type="avg",
                                           global_pooling=True,
                                           data_format="NHWC")
                logits = fluid.layers.fc(pool, size=3)
                loss = fluid.layers.mean(
                    fluid.layers.softmax_with_cross_entropy(logits, label))
                fluid.optimizer.SGD(0.1).minimize(loss, startup)
            scope = fluid.Scope()
            exe = fluid.Executor()
            exe.run(startup, scope=scope)
            rng = np.random.RandomState(0)
            feed = {"img": rng.normal(0, 1, (4, 8, 8, 4)).astype("float32"),
                    "label": rng.randint(0, 3, (4, 1)).astype("int64")}
            return [float(exe.run(main, feed=feed, fetch_list=[loss],
                                  scope=scope)[0]) for _ in range(4)]
        finally:
            set_flags({"conv_1x1_grad_as_dot": False})

    base = train_once(False)
    dot = train_once(True)
    np.testing.assert_allclose(dot, base, rtol=1e-5, atol=1e-6)
    assert base[-1] < base[0]

    # the flag branch must actually ENGAGE (otherwise this parity test is
    # vacuous): with the flag on, the grad lowering of an eligible 1x1 conv
    # must contain dot_general and no transposed convolution
    import jax
    import jax.numpy as jnp

    set_flags({"conv_1x1_grad_as_dot": True})
    try:
        # eager-run the registered grad-op lowering and inspect its jaxpr
        from paddle_tpu.core.registry import get_op_info
        info = get_op_info("conv2d_grad")

        class _Op:
            type = "conv2d_grad"
            attrs = {"data_format": "NHWC", "strides": [1, 1],
                     "paddings": [0, 0], "dilations": [1, 1], "groups": 1}
            def input(self, s):
                return [s]
            def output(self, s):
                return [s + "_out"]
            def output_arg_names(self):
                return ["Input@GRAD_out", "Filter@GRAD_out"]

        class _Ctx:
            op = _Op()
            def __init__(self, env):
                self.env = env
            def input(self, s):
                return self.env[s]
            def has_input(self, s):
                return s in self.env
            def attr(self, n, d=None):
                return _Op.attrs.get(n, d)
            def set_output(self, s, v):
                self.env[s + "_out"] = v

        def run_grad(xv, wv, dyv):
            ctx = _Ctx({"Input": xv, "Filter": wv, "Output@GRAD": dyv})
            info.forward(ctx)
            return ctx.env["Input@GRAD_out"], ctx.env["Filter@GRAD_out"]

        jaxpr = str(jax.make_jaxpr(run_grad)(
            jnp.zeros((2, 4, 4, 3)), jnp.zeros((5, 3, 1, 1)),
            jnp.zeros((2, 4, 4, 5))))
        assert "dot_general" in jaxpr, jaxpr
        assert "conv_general_dilated" not in jaxpr, jaxpr
    finally:
        set_flags({"conv_1x1_grad_as_dot": False})
