"""infer_shape-coverage gate (the test_flags_doc.py shape: run the repo
tool as a subprocess, gate tier-1 on its exit code): a newly registered
forward op must carry an ``infer_shape`` rule — or be explicitly
grandfathered in ``tools/op_inventory.py``'s INFER_SHAPE_EXEMPT — so it
cannot dodge the verifier's shadow-inference pass; stale exemptions fail
too, so the grandfather list only ratchets down."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "op_inventory.py")


def test_every_forward_op_has_infer_shape_or_exemption():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, TOOL, "--check"],
                       capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout


def test_checker_actually_detects_dodging():
    """Pin the detection path, not just the happy path: an op missing
    infer_shape that is NOT exempted must fail the check."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import op_inventory as mod
    finally:
        sys.path.pop(0)
    import paddle_tpu.ops  # noqa: F401
    from paddle_tpu.core.registry import _REGISTRY

    # the exemption list must be a strict subset of the registry (no typos)
    fwd = {k for k in _REGISTRY if not k.endswith("_grad")}
    assert mod.INFER_SHAPE_EXEMPT <= fwd

    # simulate a dodging op: drop one exemption and assert check_infer_shape
    # would flag it (same code path, in-process)
    victim = sorted(mod.INFER_SHAPE_EXEMPT)[0]
    assert _REGISTRY[victim].infer_shape is None
    old = mod.INFER_SHAPE_EXEMPT
    mod.INFER_SHAPE_EXEMPT = old - {victim}
    try:
        assert mod.check_infer_shape() == 1
    finally:
        mod.INFER_SHAPE_EXEMPT = old
