"""Expert parallelism (MoE all-to-all) and pipeline parallelism (GPipe
microbatch ring) — the TPU-native parallelism modes the reference never had
(SURVEY.md §2.3 checklist: "tensor/pipeline/sequence/expert parallelism =
TPU-native new work").
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.parallel import (make_mesh, moe_ffn, init_moe_params,
                                 shard_moe_params, pipeline_apply,
                                 shard_pipeline_params,
                                 pipeline_stack_reference)


def test_moe_ffn_sharded_matches_replicated():
    """Expert-sharded MoE output must equal the unsharded computation, and
    the [E, C, d] intermediates must actually shard over ep."""
    rng = jax.random.PRNGKey(0)
    n, d, h, e = 64, 16, 32, 8
    params = init_moe_params(rng, d, h, e)
    x = jax.random.normal(jax.random.PRNGKey(1), (n, d))

    ref, ref_aux = moe_ffn(x, params, mesh=None)

    mesh = make_mesh(8, axes=("ep",))
    sharded = shard_moe_params(params, mesh)
    with mesh:
        got, aux = jax.jit(
            lambda xv, p: moe_ffn(xv, p, mesh=mesh))(x, sharded)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(float(aux), float(ref_aux), rtol=1e-5)
    # expert weights are genuinely distributed
    assert "ep" in str(sharded["w_in"].sharding.spec)


def test_moe_trains_and_balances():
    """A routed MoE regression head trains; the aux loss keeps more than
    one expert in play."""
    rng = jax.random.PRNGKey(2)
    n, d, h, e = 128, 8, 16, 4
    params = init_moe_params(rng, d, h, e)
    mesh = make_mesh(4, axes=("ep",))
    params = shard_moe_params(params, mesh)
    w_true = jax.random.normal(jax.random.PRNGKey(3), (d, d))
    x = jax.random.normal(jax.random.PRNGKey(4), (n, d))
    y = jnp.tanh(x @ w_true)

    @jax.jit
    def step(p):
        def loss_fn(p):
            out, aux = moe_ffn(x, p, mesh=mesh)
            return jnp.mean((out - y) ** 2) + 0.01 * aux
        l, g = jax.value_and_grad(loss_fn)(p)
        return l, jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)

    with mesh:
        losses = []
        for _ in range(200):
            l, params = step(params)
            losses.append(float(l))
    assert losses[-1] < 0.6 * losses[0], (losses[0], losses[-1])


@pytest.mark.parametrize("n_micro", [4, 9])
def test_pipeline_matches_sequential(n_micro):
    """The M+S-1-tick ppermute pipeline computes exactly the sequential
    stage fold."""
    s, mb, d = 4, 8, 16
    ws = jax.random.normal(jax.random.PRNGKey(5), (s, d, d)) * 0.3
    xs = jax.random.normal(jax.random.PRNGKey(6), (n_micro, mb, d))

    def stage(w, x):
        return jnp.tanh(x @ w)

    ref = pipeline_stack_reference(stage, ws, xs)
    mesh = make_mesh(4, axes=("pp",))
    ws_sharded = shard_pipeline_params(ws, mesh)
    with mesh:
        got = jax.jit(lambda p, x: pipeline_apply(stage, p, x, mesh))(
            ws_sharded, xs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_pipeline_trains_through_backward():
    """Reverse-mode AD through the pipeline (ppermute transposes to the
    reverse ring) trains the stage stack."""
    s, m, mb, d = 4, 4, 8, 8
    ws = jax.random.normal(jax.random.PRNGKey(7), (s, d, d)) * 0.3
    mesh = make_mesh(4, axes=("pp",))
    ws = shard_pipeline_params(ws, mesh)
    xs = jax.random.normal(jax.random.PRNGKey(8), (m, mb, d))
    target = jnp.tanh(jnp.tanh(xs @ jax.random.normal(
        jax.random.PRNGKey(9), (d, d))))

    def stage(w, x):
        return jnp.tanh(x @ w)

    @jax.jit
    def step(p):
        def loss_fn(p):
            out = pipeline_apply(stage, p, xs, mesh)
            return jnp.mean((out - target) ** 2)
        l, g = jax.value_and_grad(loss_fn)(p)
        return l, jax.tree_util.tree_map(lambda a, b: a - 0.5 * b, p, g)

    with mesh:
        losses = []
        for _ in range(80):
            l, p2 = step(ws)
            ws = p2
            losses.append(float(l))
    assert losses[-1] < 0.4 * losses[0], (losses[0], losses[-1])


def test_pipeline_rejects_mismatched_stage_count():
    mesh = make_mesh(4, axes=("pp",))
    ws = jnp.zeros((8, 4, 4))     # 8 stages on a 4-wide pp axis
    xs = jnp.zeros((2, 2, 4))
    with pytest.raises(ValueError, match="leading dim"):
        pipeline_apply(lambda w, x: x, ws, xs, mesh)


def test_composed_dp_pp_tp_training_step():
    """dp×pp×tp on ONE 3-axis mesh (the __graft_entry__ composed check as a
    suite test): microbatches dp-sharded, stages pp-sharded, Megatron
    column/row tp split inside each stage; fwd + grads + one SGD step match
    the sequential fold."""
    import __graft_entry__ as ge
    ge._composed_check(8)


def test_make_mesh_three_axis_default_shape():
    from paddle_tpu.parallel import make_mesh

    mesh = make_mesh(8, axes=("dp", "pp", "tp"))
    assert dict(zip(mesh.axis_names,
                    mesh.devices.shape)) == {"dp": 2, "pp": 2, "tp": 2}
