"""Memory-optimization transpiler: liveness var-reuse + early release.

Reference: python/paddle/fluid/memory_optimization_transpiler.py
(memory_optimize :189, release_memory :149) and its book re-runs
(python/paddle/fluid/tests/book_memory_optimization/) — the optimized
program must train to the same result as the unoptimized one, while the
interpreter's peak set of live temporaries shrinks.
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.memory_optimization_transpiler import (
    memory_optimize, release_memory)


def _build_mlp(seed=7):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[16])
        y = fluid.layers.data("y", shape=[1])
        h = fluid.layers.fc(input=x, size=32, act="relu")
        h = fluid.layers.fc(input=h, size=32, act="relu")
        pred = fluid.layers.fc(input=h, size=1, act=None)
        cost = fluid.layers.square_error_cost(input=pred, label=y)
        avg = fluid.layers.mean(cost)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(avg, startup)
    return main, startup, avg


def _train(main, startup, loss_name, mode, steps=6):
    rng = np.random.RandomState(0)
    xs = rng.randn(32, 16).astype("float32")
    ys = (xs.sum(axis=1, keepdims=True) * 0.1).astype("float32")
    exe = fluid.Executor(fluid.CPUPlace(), mode=mode)
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    losses = []
    for _ in range(steps):
        out, = exe.run(main, feed={"x": xs, "y": ys},
                       fetch_list=[loss_name], scope=scope)
        losses.append(float(np.asarray(out)))
    return losses


def test_memory_optimize_preserves_training():
    base_main, base_start, avg = _build_mlp()
    want = _train(base_main, base_start, avg.name, "eager")

    opt_main, opt_start, avg2 = _build_mlp()
    n = memory_optimize(opt_main, fetch_list=[avg2])
    assert n > 0, "expected at least one var reuse in fc-MLP fwd+bwd"
    got = _train(opt_main, opt_start, avg2.name, "eager")
    np.testing.assert_allclose(got, want, rtol=1e-6)
    assert got[-1] < got[0]


def test_memory_optimize_jit_parity():
    base_main, base_start, avg = _build_mlp()
    want = _train(base_main, base_start, avg.name, "jit")
    opt_main, opt_start, avg2 = _build_mlp()
    memory_optimize(opt_main, fetch_list=[avg2])
    got = _train(opt_main, opt_start, avg2.name, "jit")
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_memory_optimize_reduces_distinct_temporaries():
    main, _, avg = _build_mlp()
    before = len({n for op in main.global_block().ops
                  for n in op.output_arg_names()})
    memory_optimize(main, fetch_list=[avg])
    after = len({n for op in main.global_block().ops
                 for n in op.output_arg_names()})
    assert after < before, (before, after)


def test_release_memory_inserts_deletes_and_preserves_training():
    base_main, base_start, avg = _build_mlp()
    want = _train(base_main, base_start, avg.name, "eager")

    rel_main, rel_start, avg2 = _build_mlp()
    n = release_memory(rel_main, fetch_list=[avg2])
    assert n > 0
    types = [op.type for op in rel_main.global_block().ops]
    assert "delete_var" in types
    got = _train(rel_main, rel_start, avg2.name, "eager")
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_release_memory_deletes_land_at_death_points():
    """Every deleted name must never be READ by a later op (a later re-DEF
    is legal — delete-then-redefine)."""
    main, _, avg = _build_mlp()
    release_memory(main, fetch_list=[avg])
    ops = main.global_block().ops
    for i, op in enumerate(ops):
        if op.type != "delete_var":
            continue
        for name in op.input("X"):
            for later in ops[i + 1:]:
                if later.type == "delete_var":
                    continue
                redefined = name in later.output_arg_names()
                if redefined:
                    break
                assert name not in later.input_arg_names(), (name, later.type)


def test_skip_set_protects_fetches():
    main, _, avg = _build_mlp()
    memory_optimize(main, fetch_list=[avg])
    release_memory(main, fetch_list=[avg])
    # the fetch target must still be produced and never deleted
    produced = {n for op in main.global_block().ops
                for n in op.output_arg_names()}
    deleted = {n for op in main.global_block().ops if op.type == "delete_var"
               for n in op.input("X")}
    assert avg.name in produced
    assert avg.name not in deleted


def _build_tower():
    """Shrinking fc tower: the 8-wide temp dies before the 4-wide ones are
    defined — under name-level reuse it must NOT be renamed onto (exact
    declared shape required; see transpiler docstring on level-1)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8])
        a = fluid.layers.fc(input=x, size=8, act="relu")
        b = fluid.layers.fc(input=a, size=4, act="relu")
        c = fluid.layers.fc(input=b, size=4, act=None)
        c2 = fluid.layers.fc(input=c, size=4, act=None)
        out = fluid.layers.mean(c2)
    return main, startup, out


def test_reuse_requires_exact_shape_even_at_level1():
    base_main, base_start, out0 = _build_tower()
    exe = fluid.Executor(fluid.CPUPlace(), mode="eager")
    rng = np.random.RandomState(3)
    xv = rng.randn(5, 8).astype("float32")
    s0 = fluid.Scope()
    exe.run(base_start, scope=s0)
    want, = exe.run(base_main, feed={"x": xv}, fetch_list=[out0], scope=s0)

    n1_main, n1_start, out1 = _build_tower()
    n1_main.random_seed = base_main.random_seed
    n1 = memory_optimize(n1_main, fetch_list=[out1], level=1)
    # exact-shape reuses exist (the chained 4-wide temps) but the dead
    # 8-wide temp must not be renamed onto by a 4-wide def: declared
    # shape and runtime value stay in sync, so outputs are identical
    assert n1 > 0
    s1 = fluid.Scope()
    exe.run(n1_start, scope=s1)
    # copy base's initialized params so both programs share weights
    for name in s0.local_names():
        s1.set(name, s0.find_var(name))
    got, = exe.run(n1_main, feed={"x": xv}, fetch_list=[out1], scope=s1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_control_flow_barrier_left_alone():
    """Programs with sub-block ops keep every sub-block-touched name."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        h = fluid.layers.fc(input=x, size=4, act="relu")
        seq = fluid.layers.data("seq", shape=[3, 4])
        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            inp = rnn.step_input(seq)
            mem = rnn.memory(shape=[2, 4], value=0.0)
            nxt = fluid.layers.fc(input=fluid.layers.elementwise_add(inp, mem),
                                  size=4, act="tanh")
            rnn.update_memory(mem, nxt)
            rnn.step_output(nxt)
        out = fluid.layers.mean(rnn()) + fluid.layers.mean(h)
    before = [dict(op.inputs) for op in main.global_block().ops
              if any(op.has_attr(a) for a in ("sub_block",
                                              "sub_block_false"))]
    memory_optimize(main, fetch_list=[out])
    release_memory(main, fetch_list=[out])
    after = [dict(op.inputs) for op in main.global_block().ops
             if any(op.has_attr(a) for a in ("sub_block", "sub_block_false"))]
    assert before == after
