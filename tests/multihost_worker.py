"""Worker script for the two-process multihost smoke test (spawned by
paddle_tpu.distributed.launch; see tests/test_multihost.py).

Verifies, from inside a 2-process x 4-virtual-device jax.distributed
runtime: process wiring, the DCN-major global mesh, a CROSS-PROCESS psum,
and a sharded fluid training step over the global mesh.
"""

import os
import sys

# must run before jax touches a backend (the axon sitecustomize pins TPU)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np


def main():
    from paddle_tpu.parallel.multihost import init_multihost, global_mesh

    info = init_multihost()
    assert info["process_count"] == 2, info
    assert info["local_devices"] == 4, info
    assert info["global_devices"] == 8, info

    mesh = global_mesh(axes=("dp",))
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    # cross-process psum: each device contributes its global row index
    sharding = NamedSharding(mesh, P("dp"))
    rank = info["process_index"]
    local = np.arange(rank * 4, rank * 4 + 4, dtype=np.float32)
    arr = jax.make_array_from_process_local_data(sharding, local, (8,))

    f = jax.jit(shard_map(lambda x: jax.lax.psum(x, "dp"), mesh=mesh,
                          in_specs=P("dp"), out_specs=P()))
    try:
        total = f(arr)
    except Exception as e:
        if "aren't implemented on the CPU backend" in str(e):
            # this jaxlib's CPU backend executes no cross-process
            # collectives at all (XlaRuntimeError INVALID_ARGUMENT
            # "Multiprocess computations aren't implemented on the CPU
            # backend"; its gloo transport abort()s on the sharded step
            # — probed 2026-08). Process wiring, the DCN-major global
            # mesh, and the distributed runtime handshake were all
            # verified above; report the capability gap explicitly so
            # the test can skip with the root cause instead of failing
            # tier-1 on every CPU box.
            print(f"MULTIHOST_WORKER_UNSUPPORTED: {e}")
            return 0
        raise
    got = float(np.asarray(total)[0])
    assert got == sum(range(8)), got
    print(f"psum ok: {got}")

    # a sharded fluid training step over the global mesh (dp over DCN):
    # the same shard_program_step the single-host tests run
    import paddle_tpu.fluid as fluid
    from paddle_tpu.parallel import ShardingPlan, shard_program_step
    from paddle_tpu.testing import build_mlp, mlp_feed

    main_p, startup, loss = build_mlp(dim=16, classes=4, hidden=16,
                                      opt="sgd")
    feed = mlp_feed(16, dim=16, classes=4)
    scope = fluid.Scope()
    exe = fluid.Executor(mode="jit")
    exe.run(startup, scope=scope)
    plan = ShardingPlan(mesh)
    fn, state, feeds = shard_program_step(exe, main_p, feed, [loss], plan,
                                          scope=scope)
    losses = []
    with mesh:
        for _ in range(3):
            state, fetches = fn(state, feeds)
            losses.append(float(np.asarray(fetches[0])))
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses
    print(f"sharded step ok: {losses[0]:.4f} -> {losses[-1]:.4f}")
    print("MULTIHOST_WORKER_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
