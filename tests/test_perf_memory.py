"""Device-memory watermarks (obs.perf): the sample is json-safe, sets
the ``paddle_tpu_device_bytes_live`` gauge, shows up in a LIVE
``ModelServer.health()`` scrape on CPU, and the engines' ``stats()``
reconcile their arena/parameter accounting against the device total.
"""

import json

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.obs import perf
from paddle_tpu.obs.metrics import REGISTRY
from paddle_tpu.testing.models import build_mlp, export_tiny_lm


def _export_mlp(tmp_path):
    main, startup, _loss, logits = build_mlp(return_logits=True)
    exe = fluid.Executor()
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    d = str(tmp_path / "bundle")
    fluid.io.save_inference_model(d, ["img"], [logits], exe, main,
                                  scope=scope)
    return d


def test_sample_is_json_safe_and_sets_gauge():
    # materialize at least one live array so the CPU tally is nonzero
    import jax.numpy as jnp
    keep = jnp.zeros((64, 64), jnp.float32)
    s = perf.sample_device_memory()
    json.dumps(s)                                 # json-safe end to end
    assert s["total"] >= keep.nbytes
    assert s["devices"] and all(isinstance(v, int)
                                for v in s["devices"].values())
    # CPU backend has no allocator stats — the live-arrays tally rules
    assert set(s["sources"].values()) == {"live_arrays"}
    fam = REGISTRY.get("paddle_tpu_device_bytes_live")
    snap = fam.snapshot()
    assert snap["values"], "gauge has no children after a sample"
    assert sum(v["value"] for v in snap["values"]) == s["total"]
    json.dumps(perf.memory_section())


def test_memory_sampler_background_cadence():
    import time
    sampler = perf.MemorySampler(interval_s=0.01)
    assert not sampler.running()
    sampler.start()
    try:
        deadline = time.monotonic() + 2.0
        while sampler.samples < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        assert sampler.stop()
    assert sampler.samples >= 3
    assert not sampler.running()
    st = sampler.stats()
    assert st["last_error"] is None
    json.dumps(st)
    # restartable after stop
    sampler.start()
    assert sampler.running()
    assert sampler.stop()


def test_memory_sampler_cost_bounded_backoff():
    """A sampler can never steal more than ~1/cost_factor of a core:
    the wait stretches to cost_factor x the observed sample duration
    (the CPU live-arrays fallback grows with the process's array
    count), and sample_now() primes the stretch up front."""
    sampler = perf.MemorySampler(interval_s=0.001, cost_factor=50.0)
    out = sampler.sample_now()
    assert sampler.samples == 1
    assert out["total"] >= 0
    st = sampler.stats()
    assert st["effective_interval_s"] >= st["interval_s"]
    # a synthetic 10 ms sample must stretch the cadence to >= 0.5 s
    sampler2 = perf.MemorySampler(interval_s=0.001, cost_factor=50.0)
    real = perf.sample_device_memory
    try:
        import time as _t

        def slow():
            _t.sleep(0.01)
            return real()
        perf.sample_device_memory = slow
        sampler2.sample_now()
    finally:
        perf.sample_device_memory = real
    assert sampler2.stats()["effective_interval_s"] >= 0.5


def test_model_server_health_carries_memory_live(tmp_path):
    from paddle_tpu.serving import InferClient, ModelServer
    d = _export_mlp(tmp_path)
    server = ModelServer(d, buckets=[1, 2])
    server.start()
    try:
        client = InferClient(server.address)
        try:
            health = client.health()
        finally:
            client.close()
    finally:
        server.shutdown()
    # the scrape crossed the RPC wire — inherently json-safe — and
    # carries a CURRENT sample (engine weights are live device arrays)
    mem = health["memory"]
    assert mem["total_bytes_live"] > 0
    assert mem["device_bytes_live"]
    json.dumps(health)


def test_engine_stats_reconcile_param_bytes(tmp_path):
    from paddle_tpu.serving import InferenceEngine
    d = _export_mlp(tmp_path)
    eng = InferenceEngine(d, buckets=[1])
    eng.warmup()
    mem = eng.stats()["memory"]
    # the MLP's weights: 16x32 + 32 + 32x4 + 4 floats (+ rng key)
    assert mem["param_bytes"] >= (16 * 32 + 32 + 32 * 4 + 4) * 4
    assert mem["device_bytes_live"] >= mem["param_bytes"]
    assert mem["unaccounted_bytes"] >= 0


def test_genengine_stats_reconcile_arena_bytes(tmp_path):
    from paddle_tpu.serving.generate import GenerationEngine
    d = str(tmp_path / "lm")
    export_tiny_lm(d)
    eng = GenerationEngine(d, max_seqs=2, max_len=32, num_blocks=32,
                           block_size=16)
    eng.warmup()
    mem = eng.stats()["memory"]
    # K+V arenas: 2 layers x 2 (k, v) x [32 blocks, 16, 2 heads, 8] f32
    assert mem["arena_bytes"] == 2 * 2 * 32 * 16 * 2 * 8 * 4
    assert mem["arena_bytes_in_use"] == 0          # nothing admitted yet
    eng.start([1, 2, 3], 4)
    assert eng.stats()["memory"]["arena_bytes_in_use"] > 0
    assert mem["param_bytes"] > 0
    assert mem["device_bytes_live"] >= mem["arena_bytes"]


def test_gauge_slo_able_via_rule_engine():
    """The watermark is judged by the PR-12 rule engine with zero new
    machinery: a value-reducer rule over the gauge breaches when live
    bytes exceed the objective."""
    from paddle_tpu.obs.slo import SloMonitor
    import jax.numpy as jnp
    keep = jnp.ones((128, 128), jnp.float32)       # noqa: F841 (live)
    perf.sample_device_memory()
    mon = SloMonitor(
        [{"name": "device_mem", "objective": 1.0, "reducer": "value",
          "metric": "paddle_tpu_device_bytes_live", "agg": "sum",
          "windows": [[0.001, 1.0]]}],
        emit_metrics=False)
    status = mon.evaluate_once()
    assert status["device_mem"]["value"] >= keep.nbytes
    assert not status["device_mem"]["ok"]
