#!/usr/bin/env python
"""Guard against flags-vs-docs drift: every ``DEFINE_flag`` name in
``paddle_tpu/core/flags.py`` must appear as a row in the README's flags
table (a ``| `name` | ... |`` line). Regex-parses the source instead of
importing it, so the check runs without a jax runtime (and without
paying the package import in CI).

Exit 0 when the docs cover every flag; exit 1 listing the missing ones.
Wired into tier-1 via tests/test_flags_doc.py.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FLAGS_PY = os.path.join(REPO, "paddle_tpu", "core", "flags.py")
README = os.path.join(REPO, "README.md")


def defined_flags(flags_src):
    """DEFINE_flag("name", ...) occurrences, in definition order."""
    return re.findall(r'DEFINE_flag\(\s*["\']([A-Za-z0-9_]+)["\']',
                      flags_src)


def documented_flags(readme_src):
    """Flag names with a markdown table row: | `name` | ... |"""
    return set(re.findall(r'^\|\s*`([A-Za-z0-9_]+)`\s*\|', readme_src,
                          flags=re.MULTILINE))


def main():
    with open(FLAGS_PY) as f:
        flags = defined_flags(f.read())
    if not flags:
        print(f"check_flags_doc: no DEFINE_flag found in {FLAGS_PY} — "
              "the parser is broken, not the docs", file=sys.stderr)
        return 1
    with open(README) as f:
        documented = documented_flags(f.read())
    missing = [n for n in flags if n not in documented]
    if missing:
        print("check_flags_doc: flags missing from the README flags "
              f"table ({len(missing)} of {len(flags)}):", file=sys.stderr)
        for n in missing:
            print(f"  | `{n}` | <default> | <what it does> |",
                  file=sys.stderr)
        print("add a row per flag to the 'Flags' table in README.md",
              file=sys.stderr)
        return 1
    print(f"check_flags_doc: OK — {len(flags)} flags all documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
