"""Shared build/feed scaffolding for the profiling CLIs.

``tools/profile_step.py`` and ``tools/hlo_report.py`` used to duplicate
the flagship ResNet-50 build (program + pre-staged bf16 feeds + jit
executor + startup under bf16 matmul precision); this module is the one
copy, plus a ``--bundle`` target so ANY published model — a
``save_inference_model`` export dir or a registry ``<model>/<version>``
dir — can be profiled, not just the flagship.

Both CLIs consume a :class:`Target`: the program, a rotating feed list,
the fetch names, the executor/scope that would dispatch it in
production, and a ``ctx()`` context manager reproducing the numeric
environment the target trains/serves under.
"""

from __future__ import annotations

import contextlib
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np


class Target:
    """One profilable dispatch: ``exe.run(program, feed=feeds[i],
    fetch_list=fetch_names, scope=scope)`` under ``ctx()``."""

    def __init__(self, label, program, feeds, fetch_names, exe, scope,
                 ctx=None):
        self.label = label
        self.program = program
        self.feeds = list(feeds)
        self.fetch_names = list(fetch_names)
        self.exe = exe
        self.scope = scope
        self._ctx = ctx

    def ctx(self):
        return self._ctx() if self._ctx is not None \
            else contextlib.nullcontext()

    def step_fn(self):
        """A zero-arg one-dispatch callable cycling the staged feeds —
        what ``obs.perf.profile`` drives."""
        i = [0]

        def step():
            feed = self.feeds[i[0] % len(self.feeds)]
            i[0] += 1
            return self.exe.run(self.program, feed=feed,
                                fetch_list=self.fetch_names,
                                scope=self.scope, return_numpy=False)
        return step


def add_target_args(ap):
    """The target-selection arguments both CLIs share."""
    ap.add_argument("--batch", type=int, default=256,
                    help="batch size (flagship default 256; bundle "
                         "targets synthesize feeds at this many rows)")
    ap.add_argument("--bundle", default=None, metavar="DIR",
                    help="profile the save_inference_model / registry "
                         "version bundle at DIR instead of building the "
                         "flagship ResNet-50 training step")
    ap.add_argument("--no-s2d", action="store_true",
                    help="flagship only: disable the space-to-depth "
                         "stem rewrite")


def build_target(args):
    return build_bundle(args.bundle, batch=args.batch) if args.bundle \
        else build_flagship(args.batch, no_s2d=args.no_s2d)


def build_flagship(batch, image_size=224, class_dim=1000, no_s2d=False):
    """The exact bench.py flagship training step: ResNet-50, bf16
    feeds pre-staged on device, jit + donation + AMP executor, startup
    run under bf16 matmul precision."""
    import jax
    import jax.numpy as jnp
    import bench
    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.flags import set_flags

    set_flags({"conv_space_to_depth": not no_s2d})
    main_prog, startup, avg_loss = bench.build(batch, image_size, class_dim)
    rng = np.random.RandomState(0)
    feeds = [{
        "img": jax.device_put(
            rng.normal(0, 1, (batch, image_size, image_size, 3))
            .astype("float32")).astype(jnp.bfloat16),
        "label": jax.device_put(
            rng.randint(0, class_dim, (batch, 1)).astype("int32")),
    } for _ in range(2)]
    scope = fluid.Scope()
    exe = fluid.Executor(mode="jit", donate=True, amp=True)

    def ctx():
        return jax.default_matmul_precision("bfloat16")

    with ctx():
        exe.run(startup, scope=scope)
    return Target(f"flagship resnet50 bs{batch}", main_prog, feeds,
                  [avg_loss.name], exe, scope, ctx=ctx)


def build_bundle(model_dir, batch=1):
    """Any published model: load the bundle into a private scope (the
    serving engine's load path) and synthesize a ``batch``-row template
    feed from the program's feed-var metadata."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.obs import perf

    scope = fluid.Scope()
    exe = fluid.Executor(mode="jit")
    program, feed_names, fetch_vars = fluid.io.load_inference_model(
        model_dir, exe, scope=scope)
    from paddle_tpu.serving.engine import commit_scope_arrays
    commit_scope_arrays(scope)
    feed = perf.template_feed(program, feed_names, batch=batch)
    fetch_names = [v if isinstance(v, str) else v.name for v in fetch_vars]
    return Target(f"bundle {model_dir} bs{batch}", program, [feed],
                  fetch_names, exe, scope)
