#!/usr/bin/env python
"""Standalone kernel-autotune driver for any saved bundle.

Does what ``ModelRegistry.warm(tune=True)`` does at publish time, but for
an arbitrary bundle dir (a registry version dir or a raw
``save_inference_model`` export): warm a throwaway engine under
``ops.autotune.capture`` to learn the REAL dispatch keys, measure every
captured key's registered variants (interleaved best-of-N windows), and
persist the winning table.

The table lands under ``<bundle>/tune/`` by default — when the bundle
carries a registry ``VERSION.json`` its ``tune_files`` digests are
updated in place (tmp + os.replace, the registry's certify semantics) so
replicas resolving the version load the table manifest-pinned and
``registry.verify`` keeps re-hashing it. ``--out`` writes to a plain
directory instead (point serving at it via the ``kernel_autotune_dir``
flag) and leaves any manifest alone.

Usage:
  python tools/autotune.py BUNDLE [--model-kind auto|feedforward|generative]
         [--buckets 1,8] [--repeats 3] [--inner 2] [--bf16] [--out DIR]
"""

import argparse
import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _sha256_file(path):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _bundle_model_kind(bundle, requested):
    if requested != "auto":
        return requested
    try:
        with open(os.path.join(bundle, "VERSION.json")) as f:
            return json.load(f).get("model_kind", "feedforward")
    except (OSError, ValueError):
        return "feedforward"


def _capture_keys(bundle, model_kind, buckets):
    from paddle_tpu.ops import autotune as at
    if model_kind == "generative":
        from paddle_tpu.serving import GenerationEngine
        engine = GenerationEngine(bundle, exec_cache=False)
        with at.capture() as keys:
            engine.warmup()
    else:
        from paddle_tpu.serving import InferenceEngine
        engine = InferenceEngine(bundle, buckets=buckets, exec_cache=False)
        with at.capture() as keys:
            engine.warmup()
    return keys


def _certify_manifest(bundle, store):
    """Update the bundle's VERSION.json ``tune_files`` to exactly the
    artifacts this run touched, pruning stale tables — no-op when the
    bundle has no manifest (a raw export: the artifact self-digest is
    the integrity layer)."""
    from paddle_tpu.ops import autotune as at
    mpath = os.path.join(bundle, "VERSION.json")
    try:
        with open(mpath) as f:
            m = json.load(f)
    except (OSError, ValueError):
        return None
    touched = set(store.touched())
    tune_files = {}
    for name in sorted(os.listdir(store.path)):
        fpath = os.path.join(store.path, name)
        if not os.path.isfile(fpath) or name.endswith(".tmp"):
            continue
        if name in touched:
            tune_files[f"{at.TUNE_DIRNAME}/{name}"] = _sha256_file(fpath)
        elif name.endswith(at.ARTIFACT_SUFFIX):
            try:
                os.unlink(fpath)
            except OSError:
                pass
    if m.get("tune_files") != tune_files:
        m["tune_files"] = tune_files
        tmp = mpath + ".tmp"
        with open(tmp, "w") as f:
            json.dump(m, f, indent=1, sort_keys=True)
        os.replace(tmp, mpath)
    return tune_files


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="measure per-shape kernel variants for a bundle and "
                    "persist the winning table")
    ap.add_argument("bundle", help="registry version dir or raw export")
    ap.add_argument("--model-kind", default="auto",
                    choices=("auto", "feedforward", "generative"),
                    help="engine class; auto reads the bundle's "
                         "VERSION.json (default feedforward)")
    ap.add_argument("--buckets", default=None,
                    help="feed-forward warmup buckets, e.g. '1,8'")
    ap.add_argument("--repeats", type=int, default=3,
                    help="best-of-N timing windows per variant")
    ap.add_argument("--inner", type=int, default=2,
                    help="calls per timing window")
    ap.add_argument("--bf16", action="store_true",
                    help="let the tuner consider value-changing "
                         "bf16-flagged variants (kernel_autotune_bf16)")
    ap.add_argument("--out", default=None,
                    help="write the table to this plain dir instead of "
                         "<bundle>/tune/ (no manifest update)")
    args = ap.parse_args(argv)

    bundle = os.path.abspath(args.bundle)
    if not os.path.isdir(bundle):
        print(f"autotune: {bundle!r} is not a directory", file=sys.stderr)
        return 2

    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.ops import autotune as at
    if args.bf16:
        set_flags({"kernel_autotune_bf16": True})

    kind = _bundle_model_kind(bundle, args.model_kind)
    keys = _capture_keys(bundle, kind, args.buckets)
    print(f"autotune: captured {len(keys)} dispatches "
          f"({len({(k, at.key_str(key)) for k, key, _ in keys})} distinct "
          f"keys) from {kind} warmup")

    out_dir = args.out or os.path.join(bundle, at.TUNE_DIRNAME)
    store = at.TuneStore(out_dir)
    table = at.Tuner(repeats=args.repeats, inner=args.inner) \
        .tune(keys, table=store.load())
    path = store.save(table)
    if path is None:
        print(f"autotune: could not write a table under {out_dir!r}",
              file=sys.stderr)
        return 1
    if args.out is None:
        _certify_manifest(bundle, store)

    for (kernel, ks), e in sorted(table.entries.items()):
        timed = ", ".join(f"{n}={ms:.3f}ms"
                          for n, ms in sorted(e["timings_ms"].items()))
        print(f"  {kernel}: {e['variant']}"
              + (f"  [{timed}]" if timed else "  [only candidate]")
              + f"  key={ks}")
    print(f"autotune: {len(table.entries)} entries -> {path} "
          f"(digest {table.digest()[:12]}…)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
