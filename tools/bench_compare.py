#!/usr/bin/env python
"""Bench regression gate: machine-diff two bench runs per lane.

Accepts three record sources, auto-detected per file:

* a driver ``BENCH_r*.json`` (``{"n", "cmd", "tail", ...}`` — lane
  records are the JSON lines inside ``tail``),
* a raw bench.py output file (one JSON object per line, non-JSON lines
  ignored),
* a plain JSON list/object of lane records.

Lane records are the ``{"metric", "value", "unit", ...}`` rows bench.py
prints; ``_smoke`` suffixes are stripped so a smoke run compares against
a full run of the same lane. Direction comes from the unit string: units
starting with ``ms``/``%`` or saying "lower is better" regress UP,
everything else (img/s, QPS, MB/s, tokens/s, x-speedups) regresses DOWN.
In ``--dir`` trajectory mode a lane whose two records carry different
``backend`` stamps is skipped with a one-line note — a CPU-smoke number
diffed against a TPU number is a machine change, not a regression.

Exit codes (the tier-1 subprocess gate pins all three):

* ``0`` — every lane within the noise threshold (default 5%),
* ``1`` — at least one regression, named in the table,
* ``2`` — typed input failure: unreadable/malformed records, a record
  without metric/value, or a lane present in OLD but missing from NEW
  (``--ignore-missing`` downgrades the last to a note).

Usage:
    python tools/bench_compare.py OLD.json NEW.json [--threshold 5]
    python tools/bench_compare.py --dir .      # two newest BENCH_r*.json
In-process: ``bench.py --compare-to PREV.json`` runs compare_records()
and stamps the verdict into the final flagship record.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys


class BenchCompareError(ValueError):
    """Typed input failure: malformed record files, lanes without
    metric/value, missing lanes — exit code 2, never a traceback."""


def _lane_name(metric):
    return re.sub(r"_smoke$", "", str(metric))


def _coerce_records(objs, path):
    out = {}
    for o in objs:
        if not isinstance(o, dict) or "metric" not in o:
            continue
        if "value" not in o or not isinstance(o["value"], (int, float)) \
                or isinstance(o["value"], bool):
            raise BenchCompareError(
                f"{path}: lane {o.get('metric')!r} has no numeric "
                f"'value' field (got {o.get('value')!r})")
        out[_lane_name(o["metric"])] = o
    if not out:
        raise BenchCompareError(
            f"{path}: no bench lane records found (expected JSON lines "
            "with 'metric' and 'value' fields, a driver BENCH_r*.json "
            "with them in 'tail', or a JSON list of records)")
    return out


def load_records(path):
    """``{lane: record}`` from any supported file shape. Raises
    :class:`BenchCompareError` on unreadable/malformed input."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        raise BenchCompareError(f"cannot read {path}: {e}") from e

    def json_lines(s):
        objs = []
        for ln in s.splitlines():
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                objs.append(json.loads(ln))
            except ValueError:
                continue
        return objs

    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and "tail" in doc:
        objs = json_lines(doc.get("tail") or "")
        if isinstance(doc.get("parsed"), dict):
            objs.append(doc["parsed"])
    elif isinstance(doc, dict) and "metric" in doc:
        objs = [doc]
    elif isinstance(doc, list):
        objs = doc
    elif doc is None:
        objs = json_lines(text)
    else:
        raise BenchCompareError(
            f"{path}: unrecognized record shape "
            f"({type(doc).__name__} without 'tail'/'metric')")
    return _coerce_records(objs, path)


def lower_is_better(record):
    # "s " covers second-denominated latency lanes (warm_start_serving's
    # "s replica time-to-ready ..."), exactly like the ms-denominated
    # ones; "s/step"-style throughput units don't start with "s " so
    # they keep the higher-is-better default. Ratio lanes where smaller
    # wins (reload_storm_serving's "x TTFT p99 ... reload vs steady")
    # say "lower is better" in their unit string explicitly — "x ..."
    # alone stays higher-is-better (speedup lanes)
    unit = str(record.get("unit", ""))
    return ("lower is better" in unit or unit.startswith("ms")
            or unit.startswith("s ") or unit.startswith("%"))


def compare_records(old, new, threshold_pct=5.0, backend_skip=False):
    """Per-lane delta of two ``{lane: record}`` maps. Returns
    ``{rows, regressions, missing, new_lanes, backend_skipped, ok,
    threshold_pct}`` — ``ok`` ignores missing lanes (the CLI decides
    their severity). With ``backend_skip`` (trajectory mode), a lane
    whose two records carry DIFFERENT ``backend`` stamps is excluded
    from the delta instead of compared: a CPU-smoke number diffed
    against a TPU number is neither a regression nor an improvement,
    it's a different machine."""
    rows, regressions, missing, backend_skipped = [], [], [], []
    thr = float(threshold_pct) / 100.0
    for lane in sorted(old):
        o = old[lane]
        n = new.get(lane)
        if n is None:
            missing.append(lane)
            continue
        if backend_skip and o.get("backend") != n.get("backend"):
            backend_skipped.append(lane)
            continue
        ov, nv = float(o["value"]), float(n["value"])
        lib = lower_is_better(o)
        if ov == 0.0:
            delta = 0.0 if nv == 0.0 else float("inf") * (1 if nv > 0 else -1)
        else:
            delta = (nv - ov) / abs(ov)
        regressed = (delta > thr) if lib else (delta < -thr)
        improved = (delta < -thr) if lib else (delta > thr)
        rows.append({
            "lane": lane, "old": ov, "new": nv,
            "delta_pct": round(delta * 100.0, 2),
            "direction": "lower_is_better" if lib else "higher_is_better",
            "verdict": ("REGRESSION" if regressed
                        else "improved" if improved else "ok"),
        })
        if regressed:
            regressions.append(lane)
    return {
        "rows": rows,
        "regressions": regressions,
        "missing": missing,
        "new_lanes": sorted(set(new) - set(old)),
        "backend_skipped": backend_skipped,
        "ok": not regressions,
        "threshold_pct": float(threshold_pct),
    }


def format_table(result):
    lines = [f"{'lane':<36} {'old':>12} {'new':>12} {'delta%':>8}  verdict"]
    for r in result["rows"]:
        lines.append(f"{r['lane']:<36} {r['old']:>12.3f} {r['new']:>12.3f} "
                     f"{r['delta_pct']:>8.2f}  {r['verdict']}")
    for lane in result["missing"]:
        lines.append(f"{lane:<36} {'-':>12} {'MISSING':>12}")
    for lane in result["new_lanes"]:
        lines.append(f"{lane:<36} {'NEW':>12} {'-':>12}")
    return "\n".join(lines)


def _trajectory_pair(dirname):
    paths = glob.glob(os.path.join(dirname, "BENCH_r*.json"))

    def key(p):
        m = re.search(r"BENCH_r(\d+)", os.path.basename(p))
        return int(m.group(1)) if m else -1

    paths = sorted(paths, key=key)
    if len(paths) < 2:
        raise BenchCompareError(
            f"--dir {dirname}: need at least two BENCH_r*.json to "
            f"compare, found {len(paths)}")
    return paths[-2], paths[-1]


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="diff two bench runs per lane; nonzero exit on "
                    "regression (see module docstring for exit codes)")
    ap.add_argument("old", nargs="?", help="baseline record file")
    ap.add_argument("new", nargs="?", help="candidate record file")
    ap.add_argument("--dir", dest="trajectory_dir", default=None,
                    help="compare the two newest BENCH_r*.json in DIR "
                         "instead of explicit files")
    ap.add_argument("--threshold", type=float, default=5.0,
                    help="noise threshold in percent (default 5)")
    ap.add_argument("--ignore-missing", action="store_true",
                    help="lanes present in OLD but absent from NEW are "
                         "noted instead of failing typed")
    args = ap.parse_args(argv)

    try:
        if args.trajectory_dir:
            old_path, new_path = _trajectory_pair(args.trajectory_dir)
        elif args.old and args.new:
            old_path, new_path = args.old, args.new
        else:
            raise BenchCompareError(
                "need OLD and NEW record files (or --dir DIR)")
        old = load_records(old_path)
        new = load_records(new_path)
    except BenchCompareError as e:
        print(f"bench_compare: {e}", file=sys.stderr)
        return 2

    print(f"bench_compare: {old_path} -> {new_path} "
          f"(threshold {args.threshold:g}%)")
    # trajectory mode diffs whatever two runs landed last in the dir —
    # those can straddle backends (a CPU smoke next to a TPU run), so
    # per-lane backend stamps gate each pair; explicit OLD NEW compares
    # exactly what the caller asked for
    result = compare_records(old, new, threshold_pct=args.threshold,
                             backend_skip=bool(args.trajectory_dir))
    print(format_table(result))
    if result["backend_skipped"]:
        print("bench_compare: skipped (backend stamps differ): "
              + ", ".join(result["backend_skipped"]))
    if result["missing"] and not args.ignore_missing:
        print(f"bench_compare: lanes missing from {new_path}: "
              f"{', '.join(result['missing'])} (pass --ignore-missing "
              "to downgrade)", file=sys.stderr)
        return 2
    if result["regressions"]:
        print(f"bench_compare: REGRESSION in "
              f"{', '.join(result['regressions'])} "
              f"(> {args.threshold:g}% beyond noise)", file=sys.stderr)
        return 1
    print("bench_compare: OK — every lane within threshold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
