"""Per-kernel device timing of one dispatch of a target.

Argument parsing over ``obs.perf.profile``: runs the target's step under
``jax.profiler.trace`` and aggregates the device events (fusions,
convolutions, copies) by name — the dynamic analog of
tools/hlo_report.py's static traffic estimate, and the table the
roofline argument rests on. Default target is the flagship ResNet-50
training step exactly as bench.py runs it; ``--bundle DIR`` retargets
any ``save_inference_model`` export or registry version dir
(tools/profile_common.py is the shared scaffolding).

Usage: python tools/profile_step.py [--batch 256] [--steps 8] [--top 40]
                                    [--no-s2d] [--hlo-match DUMP.txt]
                                    [--bundle DIR]
"""

import argparse
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import profile_common


def load_hlo_annotations(path):
    """Map instruction name -> (defining line, static traffic estimate)
    from an optimized-HLO dump (tools/hlo_report.py --dump), to annotate
    fusion names with their root op and a GB/s column."""
    from paddle_tpu.obs.perf import hlo_shape_bytes
    shapes, nbytes = {}, {}
    for ln in open(path):
        m = re.match(r"\s*%?([\w.\-]+) = (.+)", ln)
        if m:
            shapes[m.group(1)] = m.group(2)[:150]
            nbytes[m.group(1)] = hlo_shape_bytes(m.group(2))
    return shapes, nbytes


def main():
    ap = argparse.ArgumentParser()
    profile_common.add_target_args(ap)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--hlo-match", default=None,
                    help="optimized-HLO dump (tools/hlo_report.py --dump) "
                         "to annotate fusion names with their root op")
    args = ap.parse_args()

    shapes, nbytes = {}, {}
    if args.hlo_match and os.path.exists(args.hlo_match):
        shapes, nbytes = load_hlo_annotations(args.hlo_match)

    from paddle_tpu.obs import perf

    target = profile_common.build_target(args)
    print(f"target: {target.label}")
    with target.ctx():
        res = perf.profile(target.step_fn(), steps=args.steps,
                           warmup=args.warmup, top=args.top)

    where = "device" if res["on_device"] else \
        "HOST (no device lanes in the trace — CPU backend)"
    print(f"wall: {res['wall_s_per_step']*1e3:.2f} ms/step   "
          f"{where} leaf total: {res['busy_us_per_step']/1e3:.2f} ms/step "
          f"over {res['steps']} steps")

    print("\nby kernel kind (trailing .N stripped):")
    for row in res["by_kind"][:15]:
        print(f"  {row['us_per_step']:10.1f} us {row['pct']:6.2f}% "
              f" {row['name']}")

    print(f"\ntop {args.top} instances (GB/s = static operand+result bytes "
          f"over measured time; v5e HBM peak ~819):")
    print(f"{'us/step':>10s} {'%':>6s} {'GB/s':>6s}  name | hlo")
    for row in res["top"]:
        us_step = row["us_per_step"]
        gbs = nbytes.get(row["name"], 0) / (us_step * 1e-6) / 1e9 \
            if us_step else 0
        print(f"{us_step:10.1f} {row['pct']:6.2f} {gbs:6.0f}  "
              f"{row['name']} | {shapes.get(row['name'], '')[:110]}")


if __name__ == "__main__":
    sys.exit(main())
