"""Per-kernel device timing of the flagship ResNet-50 training step.

Runs the exact bench.py step under jax.profiler.trace and aggregates the
/device:TPU events (fusions, convolutions, copies) by name: the dynamic
analog of tools/hlo_report.py's static traffic estimate. This is the table
the roofline argument rests on — which fusions actually burn the ~100 ms.

Usage: python tools/profile_step.py [--batch 256] [--steps 8] [--top 40]
                                    [--no-s2d] [--hlo-match DUMP.txt]
"""

import argparse
import collections
import glob
import gzip
import json
import os
import re
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def run_and_trace(batch, steps, warmup, trace_dir):
    import jax
    import jax.numpy as jnp
    import bench
    import paddle_tpu.fluid as fluid

    image_size, class_dim = 224, 1000
    main_prog, startup, avg_loss = bench.build(batch, image_size, class_dim)
    rng = np.random.RandomState(0)
    feeds = [{
        "img": jax.device_put(
            rng.normal(0, 1, (batch, image_size, image_size, 3))
            .astype("float32")).astype(jnp.bfloat16),
        "label": jax.device_put(
            rng.randint(0, class_dim, (batch, 1)).astype("int32")),
    } for _ in range(2)]

    scope = fluid.Scope()
    exe = fluid.Executor(mode="jit", donate=True, amp=True)
    with jax.default_matmul_precision("bfloat16"):
        exe.run(startup, scope=scope)
        for i in range(warmup):
            v = exe.run(main_prog, feed=feeds[i % 2], fetch_list=[avg_loss],
                        scope=scope)
        with jax.profiler.trace(trace_dir):
            t0 = time.perf_counter()
            for i in range(steps):
                v = exe.run(main_prog, feed=feeds[i % 2],
                            fetch_list=[avg_loss], scope=scope,
                            return_numpy=False)
            np.asarray(v[0])
            dt = (time.perf_counter() - t0) / steps
    return dt


def aggregate(trace_dir, steps):
    files = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    assert files, f"no trace produced under {trace_dir}"
    with gzip.open(files[0]) as f:
        tr = json.load(f)
    ev = tr.get("traceEvents", [])
    device_pids = set()
    for e in ev:
        if e.get("ph") == "M" and e.get("name") == "process_name" \
                and "TPU" in e.get("args", {}).get("name", ""):
            device_pids.add(e["pid"])
    per_name = collections.Counter()
    per_name_n = collections.Counter()
    for e in ev:
        if e.get("ph") == "X" and e.get("pid") in device_pids:
            per_name[e["name"]] += e.get("dur", 0)
            per_name_n[e["name"]] += 1
    return per_name, per_name_n


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--no-s2d", action="store_true")
    ap.add_argument("--hlo-match", default=None,
                    help="optimized-HLO dump (tools/hlo_report.py --dump) to "
                         "annotate fusion names with their root op")
    args = ap.parse_args()

    from paddle_tpu.core.flags import set_flags
    set_flags({"conv_space_to_depth": not args.no_s2d})

    shapes = {}
    nbytes = {}
    if args.hlo_match and os.path.exists(args.hlo_match):
        from hlo_report import _shape_bytes
        # map instruction name -> its defining line (shape + operands) and a
        # static traffic estimate (result + operand shapes on that line)
        for ln in open(args.hlo_match):
            m = re.match(r"\s*%?([\w.\-]+) = (.+)", ln)
            if m:
                shapes[m.group(1)] = m.group(2)[:150]
                nbytes[m.group(1)] = _shape_bytes(m.group(2))

    tmp = tempfile.mkdtemp(prefix="pdtpu_prof_")
    dt = run_and_trace(args.batch, args.steps, args.warmup, tmp)
    per_name, per_name_n = aggregate(tmp, args.steps)

    # drop the outer module/step spans: the whole-step 'jit_step(...)' event
    # and the bare per-step numeric spans nested directly under it
    leaf = {n: us for n, us in per_name.items()
            if not n.startswith("jit_") and not n.isdigit()}
    total_us = sum(leaf.values())
    print(f"wall: {dt*1e3:.2f} ms/step   device leaf-kernel total: "
          f"{total_us/args.steps/1e3:.2f} ms/step over {args.steps} steps")

    print("\nby kernel kind (trailing .N stripped):")
    grouped = collections.Counter()
    for name, us in leaf.items():
        grouped[re.sub(r"\.[0-9]+$", "", name)] += us
    for name, us in grouped.most_common(15):
        print(f"  {us/args.steps:10.1f} us {100.0*us/max(total_us,1):6.2f}% "
              f" {name}")

    print(f"\ntop {args.top} instances (GB/s = static operand+result bytes "
          f"over measured time; v5e HBM peak ~819):")
    print(f"{'us/step':>10s} {'%':>6s} {'GB/s':>6s}  name | hlo")
    for name, us in collections.Counter(leaf).most_common(args.top):
        pct = 100.0 * us / max(total_us, 1)
        us_step = us / args.steps
        gbs = nbytes.get(name, 0) / (us_step * 1e-6) / 1e9 if us_step else 0
        print(f"{us_step:10.1f} {pct:6.2f} {gbs:6.0f}  "
              f"{name} | {shapes.get(name, '')[:110]}")


if __name__ == "__main__":
    sys.exit(main())
