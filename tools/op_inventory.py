"""Reproduce the op-inventory diff against the reference's REGISTER_OP set.

Usage: python tools/op_inventory.py [--reference /root/reference]
Prints covered/missing counts and the disposition of each missing name
(every absence is a recorded redesign — see COVERAGE.md §2.2 and README
"Recorded design decisions").
"""

import argparse
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DISPOSITIONS = {
    "lod_rank_table": "redesigned: scan recurrence + reader bucketing",
    "shrink_rnn_memory": "redesigned: scan recurrence + reader bucketing",
    "reorder_lod_tensor_by_rank": "redesigned: scan recurrence",
    "split_lod_tensor": "redesigned: masked scan control flow",
    "merge_lod_tensor": "redesigned: masked scan control flow",
    "lod_tensor_to_array": "redesigned: TensorArray ops over padded LoD",
    "array_to_lod_tensor": "redesigned: TensorArray ops over padded LoD",
    "rnn_memory_helper": "redesigned: scan carries memories",
    "send": "redesigned: GSPMD collectives + distributed/ services",
    "recv": "redesigned: GSPMD collectives + distributed/ services",
    "send_barrier": "redesigned: pserver fan-in barriers (host RPC)",
    "send_vars": "redesigned: GSPMD collectives",
    "listen_and_serv": "redesigned: distributed/param_server service",
    "parallel_do": "redesigned: SPMD sharding (parallel/sharding.py)",
    "cond": "covered by conditional_block (+ lax.cond lazy form)",
    "select": "host-side fluid.Select (channels are host objects)",
    "feed": "executor-native feed (no injected ops)",
    "fetch": "executor-native fetch (no injected ops)",
    "op_name": "false positive: a macro parameter in op_registry docs",
}


def reference_ops(root):
    ops = set()
    for dirpath, _, files in os.walk(os.path.join(
            root, "paddle/fluid/operators")):
        for f in files:
            if f.endswith((".cc", ".cu.cc", ".h")):
                src = open(os.path.join(dirpath, f), errors="ignore").read()
                for m in re.finditer(
                        r"REGISTER_OP(?:ERATOR|_WITH_KERNEL"
                        r"|_WITHOUT_GRADIENT)?\(\s*([a-z0-9_]+)\s*,", src):
                    ops.add(m.group(1))
    return {o for o in ops if not o.endswith("_grad")}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reference", default="/root/reference")
    ap.add_argument("--runtime", default=None, metavar="COVERAGE_FILE",
                    help="a PDTPU_OP_COVERAGE dispatch log from a suite "
                         "run: additionally report registered ops that "
                         "NEVER DISPATCHED (stronger than word-match)")
    args = ap.parse_args()

    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import paddle_tpu.ops  # noqa: F401  (registers everything)
    from paddle_tpu.core.registry import registered_ops

    ref = reference_ops(args.reference)
    mine = {o for o in registered_ops() if not o.endswith("_grad")}
    covered = ref & mine
    missing = sorted(ref - mine)
    extra = sorted(mine - ref)

    print(f"reference op types : {len(ref)}")
    print(f"covered            : {len(covered)} "
          f"({100.0 * len(covered) / len(ref):.1f}%)")
    print(f"missing            : {len(missing)}")
    for name in missing:
        print(f"  {name:28s} {DISPOSITIONS.get(name, '?? UNRECORDED ??')}")
    undocumented = [n for n in missing if n not in DISPOSITIONS]
    print(f"tpu-native extras  : {len(extra)}")

    # every registered forward op must word-match somewhere in tests/ —
    # "registered but never numerically exercised" regressions fail here
    # (VERDICT r4 weak #4; the reference tests every op the same way:
    # python/paddle/fluid/tests/unittests/test_*_op.py)
    import glob
    text = []
    test_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests")
    for f in glob.glob(os.path.join(test_dir, "**", "*.py"), recursive=True):
        text.append(open(f, errors="ignore").read())
    words = set(re.findall(r"[A-Za-z_][A-Za-z0-9_]*", "\n".join(text)))
    untested = sorted(o for o in mine if o not in words)
    print(f"untested forward ops: {len(untested)}")
    rc = 0
    if untested:
        for name in untested:
            print(f"  UNTESTED {name}")
        rc = 1
    if undocumented:
        print(f"ERROR: undocumented missing ops: {undocumented}")
        rc = 1

    if args.runtime:
        with open(args.runtime) as f:
            dispatched = {ln.strip() for ln in f if ln.strip()}
        all_registered = set(registered_ops())
        never_fwd = sorted(o for o in all_registered
                           if not o.endswith("_grad")
                           and o not in dispatched)
        never_grad = sorted(o for o in all_registered
                            if o.endswith("_grad") and o not in dispatched)
        print(f"runtime dispatch    : {len(dispatched & all_registered)}"
              f"/{len(all_registered)} registered ops dispatched")
        print(f"never-dispatched fwd : {len(never_fwd)}")
        for n in never_fwd:
            print(f"  NEVER-RUN {n}")
        print(f"never-dispatched grad: {len(never_grad)}")
        for n in never_grad:
            print(f"  NEVER-RUN {n}")
        if never_fwd or never_grad:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
