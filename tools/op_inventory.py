"""Reproduce the op-inventory diff against the reference's REGISTER_OP set.

Usage: python tools/op_inventory.py [--reference /root/reference]
Prints covered/missing counts and the disposition of each missing name
(every absence is a recorded redesign — see COVERAGE.md §2.2 and README
"Recorded design decisions").
"""

import argparse
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# ---------------------------------------------------------------------------
# --check: registered forward ops allowed to lack an infer_shape rule.
#
# The verifier's shadow-inference pass (fluid/analysis/verify.py) re-runs
# every REGISTERED infer_shape; an op without one is invisible to it. This
# grandfather list freezes the debt at the PR-8 inventory and RATCHETS
# DOWN: a new op missing infer_shape fails --check (it must either register
# a rule or be added here with review), and a listed op that GAINS a rule
# (or disappears) also fails until removed — the list can only shrink.
# ``*_grad`` ops are exempt categorically: their output shapes are the
# forward twins' (backward._create_grad_var copies them), which the
# verifier checks directly via grad-pairing (PTL009/PTL006).
# ---------------------------------------------------------------------------
INFER_SHAPE_EXEMPT = {
    'accuracy', 'adadelta', 'adagrad',
    'adam', 'adamax', 'argmax',
    'array_length', 'assign_value', 'auc',
    'average_accumulates', 'batch_gather', 'beam_search',
    'beam_search_decode', 'bilinear_tensor_product', 'bipartite_match',
    'box_coder', 'cast', 'causal_self_attention',
    'channel_close', 'channel_create', 'channel_recv',
    'channel_send', 'chunk_eval', 'chunked_prefill_attention', 'concat',
    'conditional_block', 'conv3d', 'cos_sim',
    'create_double_buffer_reader', 'create_multi_pass_reader',
    'create_recordio_file_reader',
    'create_shuffle_reader', 'crf_decoding', 'cross_entropy',
    'ctc_align', 'decayed_adagrad', 'delete_var',
    'detection_map', 'dynamic_recurrent', 'edit_distance',
    'equal', 'fill', 'fill_constant',
    'fill_constant_batch_size_like', 'ftrl', 'fused_adam',
    'fused_momentum', 'fused_sgd', 'gather',
    'gaussian_random', 'gaussian_random_batch_size_like', 'get_places',
    'go', 'greater_equal', 'greater_than',
    'gru_unit', 'hsigmoid', 'huber_loss',
    'ifelse_merge', 'im2sequence', 'increment',
    'iou_similarity', 'is_empty', 'l1_norm',
    'less_equal', 'less_than', 'linear_chain_crf',
    'load', 'load_combine', 'lod_array_length',
    'lod_reset', 'logical_and', 'logical_not',
    'logical_or', 'logical_xor', 'lookup_table',
    'lstm_unit', 'matmul', 'max_pool2d_with_index',
    'max_pool3d_with_index', 'max_sequence_len', 'mean',
    'mine_hard_examples', 'modified_huber_loss', 'momentum',
    'mul', 'multiclass_nms', 'multiplex',
    'nce', 'not_equal', 'one_hot',
    'paged_attention', 'pool3d', 'positive_negative_pair',
    'precision_recall', 'prefill_attention', 'prior_box',
    'proximal_adagrad', 'proximal_gd', 'read',
    'read_from_array', 'recurrent', 'reduce_max',
    'reduce_mean', 'reduce_min', 'reduce_prod',
    'reduce_sum', 'reshape', 'rmsprop',
    'roi_pool', 'row_conv', 'save',
    'save_combine', 'scatter', 'sequence_concat',
    'sequence_erase', 'sequence_reshape', 'sequence_slice',
    'sgd', 'shape', 'smooth_l1_loss',
    'softmax_with_cross_entropy', 'split', 'split_ids',
    'split_selected_rows', 'spp', 'squared_l2_distance',
    'squared_l2_norm', 'sum', 'target_assign',
    'top_k', 'transpose', 'uniform_random',
    'uniform_random_batch_size_like', 'unpool', 'warpctc',
    'while', 'write_to_array',
}


def check_infer_shape():
    """--check mode (no reference checkout needed): every registered
    forward op either registers infer_shape or is in the frozen exemption
    list; stale exemptions fail too so the list only ratchets down.
    Wired into tier-1 via tests/test_op_inventory_check.py."""
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import paddle_tpu.ops  # noqa: F401  (registers everything)
    from paddle_tpu.core.registry import _REGISTRY

    fwd = {k: v for k, v in _REGISTRY.items() if not k.endswith("_grad")}
    missing = {k for k, v in fwd.items() if v.infer_shape is None}
    dodging = sorted(missing - INFER_SHAPE_EXEMPT)
    stale = sorted(n for n in INFER_SHAPE_EXEMPT
                   if n not in fwd or fwd[n].infer_shape is not None)
    rc = 0
    if dodging:
        print(f"op_inventory --check: {len(dodging)} op(s) registered "
              "WITHOUT infer_shape and not in INFER_SHAPE_EXEMPT — the "
              "verifier's shadow-inference pass cannot see them. Register "
              "an infer_shape rule (preferred) or add to the exemption "
              "list with review:")
        for n in dodging:
            print(f"  MISSING infer_shape: {n}")
        rc = 1
    if stale:
        print(f"op_inventory --check: {len(stale)} stale INFER_SHAPE_EXEMPT "
              "entrie(s) (op now has infer_shape, or is gone) — remove "
              "them so the list only shrinks:")
        for n in stale:
            print(f"  STALE exemption: {n}")
        rc = 1
    if rc == 0:
        with_rule = sum(1 for v in fwd.values() if v.infer_shape is not None)
        print(f"op_inventory --check: OK — {with_rule}/{len(fwd)} forward "
              f"ops carry infer_shape, {len(INFER_SHAPE_EXEMPT)} "
              "grandfathered (ratchet-down list)")
    return rc


DISPOSITIONS = {
    "lod_rank_table": "redesigned: scan recurrence + reader bucketing",
    "shrink_rnn_memory": "redesigned: scan recurrence + reader bucketing",
    "reorder_lod_tensor_by_rank": "redesigned: scan recurrence",
    "split_lod_tensor": "redesigned: masked scan control flow",
    "merge_lod_tensor": "redesigned: masked scan control flow",
    "lod_tensor_to_array": "redesigned: TensorArray ops over padded LoD",
    "array_to_lod_tensor": "redesigned: TensorArray ops over padded LoD",
    "rnn_memory_helper": "redesigned: scan carries memories",
    "send": "redesigned: GSPMD collectives + distributed/ services",
    "recv": "redesigned: GSPMD collectives + distributed/ services",
    "send_barrier": "redesigned: pserver fan-in barriers (host RPC)",
    "send_vars": "redesigned: GSPMD collectives",
    "listen_and_serv": "redesigned: distributed/param_server service",
    "parallel_do": "redesigned: SPMD sharding (parallel/sharding.py)",
    "cond": "covered by conditional_block (+ lax.cond lazy form)",
    "select": "host-side fluid.Select (channels are host objects)",
    "feed": "executor-native feed (no injected ops)",
    "fetch": "executor-native fetch (no injected ops)",
    "op_name": "false positive: a macro parameter in op_registry docs",
}


def reference_ops(root):
    ops = set()
    for dirpath, _, files in os.walk(os.path.join(
            root, "paddle/fluid/operators")):
        for f in files:
            if f.endswith((".cc", ".cu.cc", ".h")):
                src = open(os.path.join(dirpath, f), errors="ignore").read()
                for m in re.finditer(
                        r"REGISTER_OP(?:ERATOR|_WITH_KERNEL"
                        r"|_WITHOUT_GRADIENT)?\(\s*([a-z0-9_]+)\s*,", src):
                    ops.add(m.group(1))
    return {o for o in ops if not o.endswith("_grad")}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reference", default="/root/reference")
    ap.add_argument("--runtime", default=None, metavar="COVERAGE_FILE",
                    help="a PDTPU_OP_COVERAGE dispatch log from a suite "
                         "run: additionally report registered ops that "
                         "NEVER DISPATCHED (stronger than word-match)")
    ap.add_argument("--check", action="store_true",
                    help="infer_shape coverage gate (no reference checkout "
                         "needed): fail on registered forward ops missing "
                         "infer_shape outside the frozen INFER_SHAPE_EXEMPT "
                         "list, and on stale exemptions")
    args = ap.parse_args()

    if args.check:
        return check_infer_shape()

    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import paddle_tpu.ops  # noqa: F401  (registers everything)
    from paddle_tpu.core.registry import registered_ops

    ref = reference_ops(args.reference)
    mine = {o for o in registered_ops() if not o.endswith("_grad")}
    covered = ref & mine
    missing = sorted(ref - mine)
    extra = sorted(mine - ref)

    print(f"reference op types : {len(ref)}")
    print(f"covered            : {len(covered)} "
          f"({100.0 * len(covered) / len(ref):.1f}%)")
    print(f"missing            : {len(missing)}")
    for name in missing:
        print(f"  {name:28s} {DISPOSITIONS.get(name, '?? UNRECORDED ??')}")
    undocumented = [n for n in missing if n not in DISPOSITIONS]
    print(f"tpu-native extras  : {len(extra)}")

    # every registered forward op must word-match somewhere in tests/ —
    # "registered but never numerically exercised" regressions fail here
    # (VERDICT r4 weak #4; the reference tests every op the same way:
    # python/paddle/fluid/tests/unittests/test_*_op.py)
    import glob
    text = []
    test_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests")
    for f in glob.glob(os.path.join(test_dir, "**", "*.py"), recursive=True):
        text.append(open(f, errors="ignore").read())
    words = set(re.findall(r"[A-Za-z_][A-Za-z0-9_]*", "\n".join(text)))
    untested = sorted(o for o in mine if o not in words)
    print(f"untested forward ops: {len(untested)}")
    rc = 0
    if untested:
        for name in untested:
            print(f"  UNTESTED {name}")
        rc = 1
    if undocumented:
        print(f"ERROR: undocumented missing ops: {undocumented}")
        rc = 1

    if args.runtime:
        with open(args.runtime) as f:
            dispatched = {ln.strip() for ln in f if ln.strip()}
        all_registered = set(registered_ops())
        never_fwd = sorted(o for o in all_registered
                           if not o.endswith("_grad")
                           and o not in dispatched)
        never_grad = sorted(o for o in all_registered
                            if o.endswith("_grad") and o not in dispatched)
        print(f"runtime dispatch    : {len(dispatched & all_registered)}"
              f"/{len(all_registered)} registered ops dispatched")
        print(f"never-dispatched fwd : {len(never_fwd)}")
        for n in never_fwd:
            print(f"  NEVER-RUN {n}")
        print(f"never-dispatched grad: {len(never_grad)}")
        for n in never_grad:
            print(f"  NEVER-RUN {n}")
        if never_fwd or never_grad:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
