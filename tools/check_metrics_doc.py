#!/usr/bin/env python
"""Guard against metrics-vs-docs drift: every metric family registered
in the process-wide obs.metrics registry at import/wiring time must
appear as a row in the README's metrics table (a ``| `name` | ... |``
line) — the same ratchet shape as ``check_flags_doc.py``, so the metric
naming contract (``paddle_tpu_<subsystem>_<name>``, stable across
releases) stays enforceable.

Unlike the flags checker this one IMPORTS the wiring modules (metric
families are declared where their subsystems live — a regex over 16
files would rot); it therefore needs the package importable, and tier-1
runs it as a subprocess (tests/test_obs_plane.py).

Exit 0 when the docs cover every registered family (stale README rows
naming unregistered ``paddle_tpu_*`` metrics fail too — the ratchet cuts
both ways); exit 1 listing the drift.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
README = os.path.join(REPO, "README.md")
sys.path.insert(0, REPO)


def registered_metrics():
    """Import every module that declares metric families; return the
    registry's names. New wiring sites that register families at import
    time are picked up by importing their subsystem here."""
    import paddle_tpu  # noqa: F401  (core.executor families)
    import paddle_tpu.distributed.launch    # noqa: F401
    import paddle_tpu.distributed.param_server  # noqa: F401
    import paddle_tpu.distributed.rpc       # noqa: F401
    import paddle_tpu.obs.recorder          # noqa: F401
    import paddle_tpu.obs.slo               # noqa: F401
    import paddle_tpu.online.freezer        # noqa: F401
    import paddle_tpu.online.pool           # noqa: F401
    import paddle_tpu.online.rollout        # noqa: F401
    import paddle_tpu.online.trainer        # noqa: F401
    import paddle_tpu.ops.autotune          # noqa: F401
    import paddle_tpu.ops.pallas            # noqa: F401
    import paddle_tpu.parallel.planner      # noqa: F401
    import paddle_tpu.serving.autoscale     # noqa: F401
    import paddle_tpu.serving.batcher       # noqa: F401
    import paddle_tpu.serving.engine        # noqa: F401
    import paddle_tpu.serving.generate.kvcache    # noqa: F401
    import paddle_tpu.serving.generate.kvstore    # noqa: F401
    import paddle_tpu.serving.generate.scheduler  # noqa: F401
    import paddle_tpu.serving.router        # noqa: F401
    import paddle_tpu.serving.server        # noqa: F401
    from paddle_tpu.obs import REGISTRY
    return REGISTRY.names()


def documented_metrics(readme_src):
    """paddle_tpu_* names with a markdown table row: | `name` | ... |"""
    return set(n for n in re.findall(r'^\|\s*`([A-Za-z0-9_]+)`\s*\|',
                                     readme_src, flags=re.MULTILINE)
               if n.startswith("paddle_tpu_"))


def main():
    names = registered_metrics()
    if not names:
        print("check_metrics_doc: registry is empty after wiring imports "
              "— the checker is broken, not the docs", file=sys.stderr)
        return 1
    with open(README) as f:
        documented = documented_metrics(f.read())
    missing = [n for n in names if n not in documented]
    stale = sorted(documented - set(names))
    if missing or stale:
        if missing:
            print("check_metrics_doc: metrics missing from the README "
                  f"metrics table ({len(missing)} of {len(names)}):",
                  file=sys.stderr)
            for n in missing:
                print(f"  | `{n}` | <type> | <labels> | <what it counts> |",
                      file=sys.stderr)
        if stale:
            print("check_metrics_doc: README rows naming metrics that are "
                  f"no longer registered ({len(stale)}):", file=sys.stderr)
            for n in stale:
                print(f"  | `{n}` | ...", file=sys.stderr)
        print("keep the 'Observability' metrics table in README.md in "
              "lockstep with the registry", file=sys.stderr)
        return 1
    print(f"check_metrics_doc: OK — {len(names)} metric families all "
          "documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
