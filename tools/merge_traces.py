#!/usr/bin/env python
"""Stitch per-process chrome traces into ONE cross-process timeline.

``core.profiler.export_chrome_tracing`` writes one trace file per
process, each with its own ``perf_counter`` origin — incomparable across
processes — but stamped with a wall-clock anchor
(``otherData.epoch_origin_us``) and, since the obs plane, a ``trace_id``
on every span recorded under a propagated request id. This tool:

* loads N trace files, gives each its own pid (named after the file or
  ``--label``), and shifts every timestamp onto the EARLIEST file's
  epoch so all processes share one clock;
* emits chrome flow events (``ph`` s/t/f) linking the spans that share a
  trace id, so a single client infer through the fleet — or one trainer
  push/apply round across pserver shards — renders as one connected
  track in chrome://tracing / Perfetto;
* with ``--trace ID`` keeps only that request's spans (plus metadata).

    python tools/merge_traces.py -o merged.json client.json server.json
    python tools/merge_traces.py -o one_req.json --trace 3f2a... *.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _load(path):
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):          # bare event-array form
        doc = {"traceEvents": doc}
    return doc


def _epoch_us(doc):
    return int((doc.get("otherData") or {}).get("epoch_origin_us", 0))


def merge_trace_files(paths, labels=None, trace=None):
    """Merge chrome trace files into one document (returned as a dict).

    ``labels`` names each file's process lane (defaults to the file
    basename); ``trace`` filters to one trace id. Spans sharing a trace
    id are linked with flow events across processes."""
    docs = [_load(p) for p in paths]
    labels = list(labels or [])
    while len(labels) < len(paths):
        p = paths[len(labels)]
        labels.append(os.path.splitext(os.path.basename(p))[0])
    merged = merge_trace_docs(docs, labels, trace=trace)
    merged["otherData"]["merged_from"] = [str(p) for p in paths]
    return merged


def merge_trace_docs(docs, labels, trace=None):
    """The files-independent core of :func:`merge_trace_files`: merge
    already-loaded chrome trace documents (each with an
    ``otherData.epoch_origin_us`` anchor) onto one clock with flow links
    — also the entry point ``tools/dump_flight.py`` feeds in-memory
    documents built from flight-recorder bundles."""
    epochs = [_epoch_us(d) for d in docs]
    known = [e for e in epochs if e]
    base = min(known) if known else 0

    events = []
    by_trace = {}          # trace_id -> [(ts, pid, tid)]
    for pid, (doc, epoch, label) in enumerate(zip(docs, epochs, labels)):
        shift = (epoch - base) if epoch else 0
        events.append({"ph": "M", "pid": pid, "name": "process_name",
                       "args": {"name": label}})
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") == "M":
                continue               # per-file metadata replaced above
            tid = (ev.get("args") or {}).get("trace_id")
            if trace is not None and tid != trace:
                continue
            out = dict(ev)
            out["pid"] = pid
            out["ts"] = int(ev.get("ts", 0)) + shift
            events.append(out)
            if tid is not None:
                by_trace.setdefault(tid, []).append(
                    (out["ts"], pid, out.get("tid", 0)))

    # flow events: one arrow chain per trace id that spans >1 recorded
    # span — the visible "connected track" (bp:e binds each step to its
    # enclosing slice)
    flows = []
    for tid, points in sorted(by_trace.items()):
        if len(points) < 2:
            continue
        points.sort()
        for i, (ts, pid, thread) in enumerate(points):
            ph = "s" if i == 0 else ("f" if i == len(points) - 1 else "t")
            ev = {"ph": ph, "cat": "trace", "name": f"trace/{tid}",
                  "id": tid, "pid": pid, "tid": thread, "ts": ts}
            if ph == "f":
                ev["bp"] = "e"
            flows.append(ev)

    return {"traceEvents": events + flows, "displayTimeUnit": "ms",
            "otherData": {"epoch_origin_us": base,
                          "trace_ids": sorted(by_trace)}}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("inputs", nargs="+", metavar="trace.json",
                    help="per-process chrome trace files "
                         "(core.profiler.export_chrome_tracing output)")
    ap.add_argument("-o", "--output", required=True,
                    help="merged chrome trace to write")
    ap.add_argument("--label", action="append", default=[],
                    help="process-lane name for the Nth input "
                         "(repeatable; default: file basename)")
    ap.add_argument("--trace", default=None,
                    help="keep only spans carrying this trace id")
    args = ap.parse_args(argv)

    merged = merge_trace_files(args.inputs, labels=args.label,
                               trace=args.trace)
    with open(args.output, "w") as f:
        json.dump(merged, f)
    n_spans = sum(1 for e in merged["traceEvents"]
                  if e.get("ph") not in ("M", "s", "t", "f"))
    print(f"merge_traces: {len(args.inputs)} files -> {args.output} "
          f"({n_spans} spans, {len(merged['otherData']['trace_ids'])} "
          "trace ids linked)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
