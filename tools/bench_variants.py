"""Decompose the ResNet-50 step cost by timing model variants on the chip.

Variants:
  full        — the bench.py training step (fwd+bwd+momentum)
  fwd         — forward + loss only (infer program, no backward)
  nobn        — BN removed entirely (identity + activation): the delta vs
                full bounds BN's total cost, slightly overstating it since
                the substitute has no per-channel affine traffic at all
  bnfrozen    — BN with is_test=True (running stats; no reduction pass)

Timing rides the kernel autotuner's shared measurement core
(paddle_tpu.ops.autotune.measure): interleaved best-of-N windows across
all requested variants.

Usage: python tools/bench_variants.py [--steps 8] [--windows 3]
       [--batch 256] [--which all]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_variant(batch, image_size, class_dim, variant):
    import bench
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        shape = [image_size, image_size, 3]
        img = fluid.layers.data("img", shape=shape)
        label = fluid.layers.data("label", shape=[1], dtype="int64")

        if variant in ("nobn", "bnfrozen"):
            orig = fluid.layers.batch_norm

            def patched(input, act=None, is_test=False, **kw):
                if variant == "bnfrozen":
                    return orig(input, act=act, is_test=True, **kw)
                # nobn: identity (+act) — no normalization, no affine
                helper_out = fluid.layers.scale(input, scale=1.0)
                if act:
                    helper_out = getattr(fluid.layers, act)(helper_out)
                return helper_out

            fluid.layers.batch_norm = patched
            try:
                logits = bench.resnet50(img, class_dim)
            finally:
                fluid.layers.batch_norm = orig
        else:
            logits = bench.resnet50(img, class_dim)

        loss = fluid.layers.softmax_with_cross_entropy(logits, label)
        avg_loss = fluid.layers.mean(loss)
        if variant != "fwd":
            fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(
                avg_loss, startup)
    return main, startup, avg_loss


def build_runner(variant, batch):
    """Zero-arg timed step closure for one variant — what the shared
    measurement core (paddle_tpu.ops.autotune.measure) times. Startup
    runs here, once; the first measured call absorbs the jit compile as
    the measurement core's per-runner warmup call."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu.fluid as fluid

    image_size, class_dim = 224, 1000
    main_prog, startup, avg_loss = build_variant(batch, image_size, class_dim,
                                                 variant)
    rng = np.random.RandomState(0)
    feeds = [{
        "img": jax.device_put(rng.normal(0, 1, (batch, image_size, image_size,
                                                 3)).astype("float32")
                              ).astype(jnp.bfloat16),
        "label": jax.device_put(
            rng.randint(0, class_dim, (batch, 1)).astype("int32")),
    } for _ in range(2)]

    scope = fluid.Scope()
    exe = fluid.Executor(mode="jit", donate=(variant != "fwd"), amp=True)
    with jax.default_matmul_precision("bfloat16"):
        exe.run(startup, scope=scope)
    state = {"i": 0}

    def run():
        i = state["i"]
        state["i"] += 1
        with jax.default_matmul_precision("bfloat16"):
            v = exe.run(main_prog, feed=feeds[i % 2], fetch_list=[avg_loss],
                        scope=scope, return_numpy=False)
        return v[0]
    return run


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8,
                    help="steps per timing window")
    ap.add_argument("--windows", type=int, default=3,
                    help="best-of-N windows per variant (interleaved "
                         "across variants)")
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--which", default="all")
    args = ap.parse_args()

    # timing rides the autotuner's measurement core: ONE interleaved
    # best-of-N implementation in the tree (ops/autotune.measure), so
    # drift hits every variant's windows equally instead of biasing
    # whichever variant ran last
    from paddle_tpu.ops.autotune import measure

    variants = ["full", "fwd", "bnfrozen", "nobn"] if args.which == "all" \
        else args.which.split(",")
    runners = {v: build_runner(v, args.batch) for v in variants}
    times = measure(runners, repeats=args.windows, inner=args.steps)
    for v in variants:
        if v not in times:
            print(f"{v:10s} failed to run", flush=True)
            continue
        dt = times[v] / 1e3
        print(f"{v:10s} {times[v]:8.2f} ms/step  "
              f"({args.batch/dt:.0f} img/s)", flush=True)


if __name__ == "__main__":
    main()
