"""Decompose the ResNet-50 step cost by timing model variants on the chip.

Variants:
  full        — the bench.py training step (fwd+bwd+momentum)
  fwd         — forward + loss only (infer program, no backward)
  nobn        — BN removed entirely (identity + activation): the delta vs
                full bounds BN's total cost, slightly overstating it since
                the substitute has no per-channel affine traffic at all
  bnfrozen    — BN with is_test=True (running stats; no reduction pass)

Usage: python tools/bench_variants.py [--steps 24] [--batch 256] [--which all]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_variant(batch, image_size, class_dim, variant):
    import bench
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        shape = [image_size, image_size, 3]
        img = fluid.layers.data("img", shape=shape)
        label = fluid.layers.data("label", shape=[1], dtype="int64")

        if variant in ("nobn", "bnfrozen"):
            orig = fluid.layers.batch_norm

            def patched(input, act=None, is_test=False, **kw):
                if variant == "bnfrozen":
                    return orig(input, act=act, is_test=True, **kw)
                # nobn: identity (+act) — no normalization, no affine
                helper_out = fluid.layers.scale(input, scale=1.0)
                if act:
                    helper_out = getattr(fluid.layers, act)(helper_out)
                return helper_out

            fluid.layers.batch_norm = patched
            try:
                logits = bench.resnet50(img, class_dim)
            finally:
                fluid.layers.batch_norm = orig
        else:
            logits = bench.resnet50(img, class_dim)

        loss = fluid.layers.softmax_with_cross_entropy(logits, label)
        avg_loss = fluid.layers.mean(loss)
        if variant != "fwd":
            fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(
                avg_loss, startup)
    return main, startup, avg_loss


def run_variant(variant, batch, steps, warmup):
    import jax
    import jax.numpy as jnp
    import paddle_tpu.fluid as fluid

    image_size, class_dim = 224, 1000
    main_prog, startup, avg_loss = build_variant(batch, image_size, class_dim,
                                                 variant)
    rng = np.random.RandomState(0)
    feeds = [{
        "img": jax.device_put(rng.normal(0, 1, (batch, image_size, image_size,
                                                 3)).astype("float32")
                              ).astype(jnp.bfloat16),
        "label": jax.device_put(
            rng.randint(0, class_dim, (batch, 1)).astype("int32")),
    } for _ in range(2)]

    scope = fluid.Scope()
    exe = fluid.Executor(mode="jit", donate=(variant != "fwd"), amp=True)
    with jax.default_matmul_precision("bfloat16"):
        exe.run(startup, scope=scope)
        for i in range(warmup):
            v = exe.run(main_prog, feed=feeds[i % 2], fetch_list=[avg_loss],
                        scope=scope)
        t0 = time.perf_counter()
        for i in range(steps):
            v = exe.run(main_prog, feed=feeds[i % 2], fetch_list=[avg_loss],
                        scope=scope, return_numpy=False)
        np.asarray(v[0])
        dt = (time.perf_counter() - t0) / steps
    return dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=24)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--which", default="all")
    args = ap.parse_args()

    variants = ["full", "fwd", "bnfrozen", "nobn"] if args.which == "all" \
        else args.which.split(",")
    for v in variants:
        dt = run_variant(v, args.batch, args.steps, args.warmup)
        print(f"{v:10s} {dt*1e3:8.2f} ms/step  "
              f"({args.batch/dt:.0f} img/s)", flush=True)


if __name__ == "__main__":
    main()
