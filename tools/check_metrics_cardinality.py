#!/usr/bin/env python
"""Guard against unbounded metric-label cardinality: every label name a
registered family declares must come from the BOUNDED vocabulary below
(an enum, a process-unique instance id, or a capped funnel), and every
family whose label values can originate ON THE WIRE must keep its
``__other__`` overflow funnel working — a misbehaving peer must never be
able to grow scrape-visible series without bound.

Two checks, same ratchet shape as ``check_flags_doc.py`` /
``check_metrics_doc.py`` (tier-1 runs this as a subprocess,
tests/test_obs_plane.py):

1. **declared label sets are bounded** — import every wiring module
   (the check_metrics_doc import list), walk the registry, and fail any
   family using a label name absent from ``BOUNDED_LABELS``. Adding a
   label name here is a REVIEWED declaration that its value space is
   bounded; an undeclared name is exactly the drift this gate exists to
   catch (someone labeling by user id, method string, or file path).

2. **wire-origin funnels hold** — for each family in ``WIRE_FED``,
   exercise the funnel: push more distinct wire-supplied names than the
   cap plus a non-identifier name through the producing path and assert
   the registry children stay within cap + builtins + ``__other__``,
   with the overflow landing in ``__other__``.

Exit 0 when both hold; exit 1 listing the violations.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# label name -> why its value space is bounded. Adding a name is a
# reviewed claim; the gate fails on any label name not listed here.
BOUNDED_LABELS = {
    "instance": "process-unique obs.metrics.next_instance ids — one per "
                "component constructed, bounded by process lifetime",
    "bucket": "engine batch/prompt buckets — a small parsed flag set",
    "phase": "generation phases: prefill/chunk/decode",
    "mode": "executor modes: eager/jit",
    "op_type": "registered op types — the fixed op registry",
    "kind": "small code-site enums (retrace kinds, flight event kinds)",
    "role": "wire roles: client/server",
    "method": "RPC method names — wire-origin, funneled past "
              "WireStats._METHOD_LABEL_CAP (or non-identifier shape) "
              "into __other__ (the funnel check below asserts it)",
    "supervisor": "ChildSupervisor instance ids (next_instance)",
    "child": "supervised child indices — bounded by fleet size",
    "kernel": "Pallas kernel families — a fixed code-site set",
    "outcome": "small code-site outcome enums (freeze/rollout results)",
    "rule": "declared SLO rule names — a reviewed config set",
    "window": "declared SLO window lengths — from rule configs",
    "trigger": "incident trigger enums: breach/canary_failed/"
               "child_restart/manual",
    "site": "compile-site enums (obs.perf: jit_step/jit_scan/"
            "engine_warmup/engine_infer/genengine_*/attribute/"
            "exec_cache_save) — a fixed code-site set; per-executable "
            "identity rides the CompileRecord, never a label",
    "reason": "artifact reject reasons — the fixed enums "
              "serving.execcache.REJECT_REASONS (format/manifest/"
              "fingerprint/deserialize/run_failed), "
              "serving.generate.kvstore.REJECT_REASONS (format/"
              "manifest/fingerprint/deserialize), "
              "ops.autotune.REJECT_REASONS (format/manifest/"
              "fingerprint/deserialize) and "
              "parallel.planner.REJECT_REASONS (format/manifest/"
              "fingerprint/deserialize)",
    "variant": "registered kernel variant names — the fixed code-site "
               "set ops.autotune.VARIANTS registers (jnp/pallas/"
               "pallas_db/pallas_bf16)",
    "device": "local jax devices (platform:id) — bounded by the "
              "attached hardware",
    "tenant": "tenant ids — wire-origin, funneled past "
              "serving_tenant_label_cap (or non-identifier shape) into "
              "__other__ by serving.batcher.TenantQuotas (the funnel "
              "check below asserts it)",
}

# families whose label VALUES can arrive off the RPC wire; each entry
# names the wire-fed label and the funnel-exercise below must show the
# __other__ cap holding for it
WIRE_FED = {
    "paddle_tpu_wire_calls": "method",
    "paddle_tpu_wire_call_seconds": "method",
}

# tenant-labeled families: wire-fed through TenantQuotas, which owns its
# own funnel (exercised separately below — the producing path differs
# from WireStats.note)
TENANT_FED = {
    "paddle_tpu_tenant_requests": "tenant",
    "paddle_tpu_tenant_rejected": "tenant",
}


def registered_families():
    """Import every wiring module (the check_metrics_doc list) and
    return the registry's families."""
    import paddle_tpu  # noqa: F401
    import paddle_tpu.distributed.launch    # noqa: F401
    import paddle_tpu.distributed.param_server  # noqa: F401
    import paddle_tpu.distributed.rpc       # noqa: F401
    import paddle_tpu.obs.recorder          # noqa: F401
    import paddle_tpu.obs.slo               # noqa: F401
    import paddle_tpu.online.freezer        # noqa: F401
    import paddle_tpu.online.pool           # noqa: F401
    import paddle_tpu.online.rollout        # noqa: F401
    import paddle_tpu.online.trainer        # noqa: F401
    import paddle_tpu.ops.autotune          # noqa: F401
    import paddle_tpu.ops.pallas            # noqa: F401
    import paddle_tpu.parallel.planner      # noqa: F401
    import paddle_tpu.serving.autoscale     # noqa: F401
    import paddle_tpu.serving.batcher       # noqa: F401
    import paddle_tpu.serving.engine        # noqa: F401
    import paddle_tpu.serving.generate.kvcache    # noqa: F401
    import paddle_tpu.serving.generate.kvstore    # noqa: F401
    import paddle_tpu.serving.generate.scheduler  # noqa: F401
    import paddle_tpu.serving.router        # noqa: F401
    import paddle_tpu.serving.server        # noqa: F401
    from paddle_tpu.obs import REGISTRY
    return {name: REGISTRY.get(name) for name in REGISTRY.names()}


def unbounded_label_violations(families):
    """[(family, label)] for every declared label name not in the
    bounded vocabulary."""
    out = []
    for name, fam in sorted(families.items()):
        for label in fam.label_names:
            if label not in BOUNDED_LABELS:
                out.append((name, label))
    return out


def wire_funnel_violations(families):
    """Exercise the __other__ funnel on every wire-fed family; returns
    a list of violation strings (empty = funnels hold)."""
    from paddle_tpu.distributed import rpc as rpcmod

    out = []
    for fam_name, label in sorted(WIRE_FED.items()):
        fam = families.get(fam_name)
        if fam is None:
            out.append(f"{fam_name}: wire-fed family not registered "
                       "(stale WIRE_FED entry or missing wiring import)")
            continue
        if label not in fam.label_names:
            out.append(f"{fam_name}: wire-fed label {label!r} not in "
                       f"declared labels {fam.label_names}")
            continue
    # one funnel exercise drives BOTH wire families (WireStats.note is
    # the single producing path for method-labeled series): flood a
    # fresh endpoint past the cap with wire-shaped names plus one
    # non-identifier name, then assert the registry series stayed capped
    # and the overflow funneled
    ws = rpcmod.WireStats(role="cardinality_check")
    cap = ws._METHOD_LABEL_CAP
    for i in range(cap + 16):
        ws.note(f"wirefuzz_{i}", 1, 1, 0.0)
    ws.note('bad"} 1\nforged 9', 1, 1, 0.0)     # non-identifier shape
    for fam_name in WIRE_FED:
        fam = families.get(fam_name)
        if fam is None:
            continue
        methods = {key[fam.label_names.index("method")]
                   for key in fam.children()
                   if key[fam.label_names.index("role")]
                   == "cardinality_check"}
        if "__other__" not in methods:
            out.append(f"{fam_name}: flooding past the cap never funneled "
                       "into __other__ — the wire-origin funnel is gone")
        over = {m for m in methods
                if m != "__other__" and m.startswith("wirefuzz_")}
        if len(over) > cap:
            out.append(f"{fam_name}: {len(over)} distinct wire-origin "
                       f"method labels exceed the declared cap {cap}")
        forged = [m for m in methods if "\n" in m or '"' in m]
        if forged:
            out.append(f"{fam_name}: non-identifier wire name reached "
                       f"the label set verbatim: {forged!r}")
    # the tenant funnel: flood a fresh TenantQuotas past its label cap
    # with wire-shaped tenant ids plus one non-identifier name, assert
    # the tenant-labeled series stayed capped with overflow in __other__
    from paddle_tpu.serving.batcher import TenantQuotas
    tq = TenantQuotas(rate=1000.0, burst=1000, label_cap=8)
    tcap = tq._label_cap
    for i in range(tcap + 16):
        tq.try_acquire(f"tenantfuzz_{i}")
    tq.try_acquire('bad"} 1\nforged 9')            # non-identifier shape
    for fam_name, label in sorted(TENANT_FED.items()):
        fam = families.get(fam_name)
        if fam is None:
            out.append(f"{fam_name}: tenant-fed family not registered "
                       "(stale TENANT_FED entry or missing wiring "
                       "import)")
            continue
        if label not in fam.label_names:
            out.append(f"{fam_name}: tenant-fed label {label!r} not in "
                       f"declared labels {fam.label_names}")
            continue
        tenants = {key[fam.label_names.index("tenant")]
                   for key in fam.children()
                   if key[fam.label_names.index("instance")]
                   == tq.obs_instance}
        if "__other__" not in tenants:
            out.append(f"{fam_name}: flooding past the cap never "
                       "funneled into __other__ — the tenant funnel is "
                       "gone")
        over = {t for t in tenants
                if t != "__other__" and t.startswith("tenantfuzz_")}
        if len(over) > tcap:
            out.append(f"{fam_name}: {len(over)} distinct tenant labels "
                       f"exceed the declared cap {tcap}")
        forged = [t for t in tenants if "\n" in t or '"' in t]
        if forged:
            out.append(f"{fam_name}: non-identifier tenant id reached "
                       f"the label set verbatim: {forged!r}")
    return out


def main():
    families = registered_families()
    if not families:
        print("check_metrics_cardinality: registry empty after wiring "
              "imports — the checker is broken, not the metrics",
              file=sys.stderr)
        return 1
    failures = []
    for fam_name, label in unbounded_label_violations(families):
        failures.append(
            f"{fam_name}: label {label!r} is not in the bounded "
            "vocabulary (tools/check_metrics_cardinality.py "
            "BOUNDED_LABELS) — declare why its value space is bounded "
            "or stop labeling by it")
    failures.extend(wire_funnel_violations(families))
    if failures:
        print(f"check_metrics_cardinality: {len(failures)} violations:",
              file=sys.stderr)
        for f in failures:
            print(f"  {f}", file=sys.stderr)
        return 1
    print(f"check_metrics_cardinality: OK — {len(families)} families, "
          f"every label bounded; wire funnels hold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
