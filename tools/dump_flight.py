#!/usr/bin/env python
"""Dump the flight-recorder rings of live paddle_tpu processes into one
incident bundle.

Every ``RpcServer`` (ModelServer replicas, pserver shards, the master)
answers a built-in ``flight_dump`` method with its process's bounded
ring of structured lifecycle events (obs/recorder.py: admissions,
evictions, restarts with reasons, rollout/canary outcomes,
retry/failover/spillover decisions, Pallas fallbacks — each stamped
with the wall clock and the active distributed trace id). This CLI
scrapes one or many endpoints CONCURRENTLY and writes the merged bundle:
events from every reachable process on ONE clock, sources labeled,
cross-process trace ids listed under ``linked_traces``.

    python tools/dump_flight.py 127.0.0.1:7000 127.0.0.1:7001
    python tools/dump_flight.py 127.0.0.1:7000 -o incident.json
    python tools/dump_flight.py 127.0.0.1:7000 --chrome incident_trace.json

``--chrome`` additionally renders the bundle as a chrome trace (one
process lane per source, instant events, trace-id flow arrows) through
the tools/merge_traces.py machinery — open it in chrome://tracing /
Perfetto next to profiler traces of the same incident.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_TOOLS))   # repo root: paddle_tpu
sys.path.insert(0, _TOOLS)                    # sibling merge_traces.py


def parse_address(s):
    host, _, port = s.rpartition(":")
    if not host or not port.isdigit():
        raise argparse.ArgumentTypeError(
            f"address {s!r} is not host:port")
    return host, int(port)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("addresses", nargs="+", type=parse_address,
                    metavar="host:port",
                    help="RpcServer endpoints to scrape flight_dump from")
    ap.add_argument("-o", "--output", default=None,
                    help="write the bundle JSON here (default: stdout)")
    ap.add_argument("--chrome", default=None, metavar="trace.json",
                    help="also render the bundle as a merged chrome "
                         "trace (flow-linked per trace id)")
    ap.add_argument("--reason", default="manual",
                    help="reason stamped into the bundle (default "
                         "'manual')")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-endpoint scrape timeout, seconds")
    ap.add_argument("--kind", action="append", default=[],
                    help="keep only events of this kind (repeatable)")
    ap.add_argument("--indent", type=int, default=2,
                    help="json indent (default 2)")
    args = ap.parse_args(argv)

    from paddle_tpu.obs import recorder as rec

    bundle = rec.capture_bundle(args.addresses, reason=args.reason,
                                timeout=args.timeout, include_local=False)
    reached = [s for s in bundle["processes"].values() if s is not None]
    if not reached:
        print("dump_flight: no endpoint answered", file=sys.stderr)
        return 1
    if args.kind:
        keep = set(args.kind)
        bundle["events"] = [e for e in bundle["events"]
                            if e["kind"] in keep]

    if args.output:
        with open(args.output, "w") as f:
            json.dump(bundle, f, indent=args.indent or None)
    else:
        json.dump(bundle, sys.stdout, indent=args.indent or None)
        sys.stdout.write("\n")

    if args.chrome:
        from merge_traces import merge_trace_docs

        docs, labels = rec.bundle_to_chrome(bundle)
        merged = merge_trace_docs(docs, labels)
        with open(args.chrome, "w") as f:
            json.dump(merged, f)
        print(f"dump_flight: chrome trace -> {args.chrome} "
              f"({len(merged['otherData']['trace_ids'])} trace ids "
              "linked)", file=sys.stderr)

    n_src = len(reached)
    print(f"dump_flight: {n_src}/{len(bundle['processes'])} endpoints, "
          f"{len(bundle['events'])} events, "
          f"{len(bundle['linked_traces'])} cross-process trace ids",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
