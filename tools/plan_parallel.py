#!/usr/bin/env python
"""Standalone placement-planner driver: render the auto-parallelism
PlacementReport for a saved bundle.

Does what ``ModelRegistry.warm(plan=True)`` does at publish time, but
for an arbitrary bundle dir (a registry version dir or a raw
``save_inference_model`` export): load the bundle into a throwaway
scope, enumerate the legal (dp, pp, tp, sp) meshes for this host's
device count, cost each candidate (measured FLOPs/bytes via
``obs.perf.attribute`` + the analytic collective model), and print the
ranked report — chosen mesh first, pruned candidates with why-notes.

With ``--out`` (or when the bundle carries a registry ``VERSION.json``,
with ``--certify``) the searched report is persisted as a ``.jplan``
artifact (parallel/planner.py's content-addressed envelope) so
replicas — or the next invocation — load instead of searching. A
fingerprint-matching existing artifact is a cache hit and re-renders
without a search.

Usage:
  python tools/plan_parallel.py --bundle DIR [--devices N]
         [--batch N] [--memory-budget BYTES] [--max-candidates N]
         [--out DIR] [--certify] [--json]
"""

import argparse
import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _sha256_file(path):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _certify_manifest(bundle, store):
    """Update the bundle's VERSION.json ``plan_files`` to exactly the
    artifacts this run touched, pruning stale plans — no-op when the
    bundle has no manifest (a raw export: the artifact self-digest is
    the integrity layer)."""
    from paddle_tpu.parallel import planner as pl
    mpath = os.path.join(bundle, "VERSION.json")
    try:
        with open(mpath) as f:
            m = json.load(f)
    except (OSError, ValueError):
        return None
    touched = set(store.touched())
    plan_files = {}
    for name in sorted(os.listdir(store.path)):
        fpath = os.path.join(store.path, name)
        if not os.path.isfile(fpath) or name.endswith(".tmp"):
            continue
        if name in touched:
            plan_files[f"{pl.PLAN_DIRNAME}/{name}"] = _sha256_file(fpath)
        elif name.endswith(pl.ARTIFACT_SUFFIX):
            try:
                os.unlink(fpath)
            except OSError:
                pass
    if m.get("plan_files") != plan_files:
        m["plan_files"] = plan_files
        tmp = mpath + ".tmp"
        with open(tmp, "w") as f:
            json.dump(m, f, indent=1, sort_keys=True)
        os.replace(tmp, mpath)
    return plan_files


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="enumerate + cost-model parallel placements for a "
                    "bundle and render the ranked report")
    ap.add_argument("--bundle", required=True,
                    help="registry version dir or raw "
                         "save_inference_model export")
    ap.add_argument("--devices", type=int, default=None,
                    help="device count to plan for (default: this "
                         "host's jax.device_count())")
    ap.add_argument("--batch", type=int, default=None,
                    help="feed batch rows to synthesize (default: the "
                         "device count, so every dp degree divides)")
    ap.add_argument("--memory-budget", type=int, default=None,
                    help="per-device memory budget in bytes (default: "
                         "the plan_memory_budget_bytes flag; 0 = off)")
    ap.add_argument("--max-candidates", type=int, default=None,
                    help="ranked candidates to keep (default: the "
                         "plan_max_candidates flag)")
    ap.add_argument("--out", default=None,
                    help="persist the report into this plan-artifact "
                         "dir instead of <bundle>/plan/")
    ap.add_argument("--certify", action="store_true",
                    help="persist under <bundle>/plan/ and update the "
                         "bundle's VERSION.json plan_files (the "
                         "registry certify semantics)")
    ap.add_argument("--json", action="store_true",
                    help="print the full report document as JSON "
                         "instead of the rendered table")
    args = ap.parse_args(argv)

    bundle = os.path.abspath(args.bundle)
    if not os.path.isdir(bundle):
        print(f"plan_parallel: {bundle!r} is not a directory",
              file=sys.stderr)
        return 2

    import jax

    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.obs import perf
    from paddle_tpu.parallel import planner as pl

    n = args.devices or jax.device_count()
    scope = Scope()
    exe = fluid.Executor()
    try:
        program, feed_names, fetch_vars = fluid.io.load_inference_model(
            bundle, exe, scope=scope)
    except (OSError, ValueError) as e:
        print(f"plan_parallel: cannot load bundle {bundle!r}: {e}",
              file=sys.stderr)
        return 2
    try:
        feed = perf.template_feed(program, feed_names,
                                  batch=args.batch or max(n, 1))
    except ValueError as e:
        print(f"plan_parallel: cannot synthesize feeds: {e}",
              file=sys.stderr)
        return 2

    store = None
    if args.out:
        store = pl.PlanStore(args.out)
    elif args.certify:
        store = pl.PlanStore(os.path.join(bundle, pl.PLAN_DIRNAME))
    else:
        # read the bundle's published plan/ dir (manifest-pinned) when
        # it exists — a matching artifact renders without a search
        store = pl.resolve_store(bundle)

    try:
        report = pl.plan(program, feed_example=feed, n_devices=n,
                         fetch_list=fetch_vars, executor=exe, scope=scope,
                         memory_budget=args.memory_budget,
                         max_candidates=args.max_candidates, store=store)
    except pl.PlanError as e:
        print(f"plan_parallel: {e}", file=sys.stderr)
        return 1

    if args.json:
        print(json.dumps(report.to_doc(), indent=1, sort_keys=True))
    else:
        print(report.render())
    if args.certify and store is not None:
        _certify_manifest(bundle, store)
    if report.chosen is None:
        print("plan_parallel: every candidate was pruned — raise the "
              "memory budget or shrink the model", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
