#!/usr/bin/env python
"""Dump the obs.metrics registry of live paddle_tpu processes.

Every ``RpcServer`` (ModelServer replicas, pserver shards, the decode
server) answers a built-in ``metrics`` method with a JSON-safe snapshot
of its process-wide registry; this CLI scrapes one or many of them and
renders the result:

    python tools/metrics_dump.py 127.0.0.1:7000
    python tools/metrics_dump.py 127.0.0.1:7000 127.0.0.1:7001 --merged
    python tools/metrics_dump.py 127.0.0.1:7000 --format prom

``--format json`` (default) prints the snapshot dict (per-address when
several addresses are given, one merged fleet view with ``--merged``);
``--format prom`` prints Prometheus text exposition (counters/gauges
verbatim, histograms as quantile summaries in seconds). Unreachable
endpoints render as null (json) / are skipped (prom, with a comment), and
the exit code is 1 when NO endpoint answered.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_address(s):
    host, _, port = s.rpartition(":")
    if not host or not port.isdigit():
        raise argparse.ArgumentTypeError(
            f"address {s!r} is not host:port")
    return host, int(port)


def readme_metric_help(readme_path=None):
    """{metric name: description} parsed from the README metrics table
    — the SAME per-family rows ``tools/check_metrics_doc.py`` validates
    against the registry, reused here so the ``# HELP`` lines in scraped
    Prometheus text carry the reviewed docs wording (the wire snapshot's
    help string is the fallback for families the table hasn't caught up
    with — the doc gate makes that a transient state)."""
    import re

    if readme_path is None:
        readme_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "README.md")
    out = {}
    try:
        with open(readme_path) as f:
            src = f.read()
    except OSError:
        return out
    row = re.compile(r'^\|\s*`(paddle_tpu_[A-Za-z0-9_]+)`\s*'
                     r'\|[^|]*\|[^|]*\|\s*([^|]+?)\s*\|', re.MULTILINE)
    for name, desc in row.findall(src):
        out[name] = desc
    return out


def apply_readme_help(snapshot, help_by_name):
    """Overlay README descriptions onto a snapshot's per-family help
    fields (in place; returns the snapshot)."""
    for name, fam in (snapshot or {}).items():
        if isinstance(fam, dict) and name in help_by_name:
            fam["help"] = help_by_name[name]
    return snapshot


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("addresses", nargs="+", type=parse_address,
                    metavar="host:port",
                    help="RpcServer endpoints to scrape (any paddle_tpu "
                         "server: ModelServer, pserver shard, ...)")
    ap.add_argument("--format", choices=("json", "prom"), default="json",
                    help="output format (default json)")
    ap.add_argument("--merged", action="store_true",
                    help="merge all endpoints into one fleet-wide "
                         "snapshot (counters sum; histogram p50/p99 take "
                         "the conservative max)")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-endpoint scrape timeout, seconds")
    ap.add_argument("--indent", type=int, default=2,
                    help="json indent (default 2)")
    args = ap.parse_args(argv)

    from paddle_tpu.obs import metrics as m

    scraped = m.scrape(args.addresses, timeout=args.timeout)
    by_addr = {f"{h}:{p}": snap for (h, p), snap in scraped.items()}
    reached = [s for s in by_addr.values() if s is not None]
    if not reached:
        print("metrics_dump: no endpoint answered", file=sys.stderr)
        return 1

    merged = len(args.addresses) == 1 or args.merged
    if args.format == "prom":
        # HELP lines come from the README metrics-table descriptions —
        # the same rows check_metrics_doc.py keeps in lockstep with the
        # registry — so scraped text is self-describing in the reviewed
        # docs wording
        doc_help = readme_metric_help()
        snap = m.merge_snapshots(reached) if merged else None
        if snap is not None:
            sys.stdout.write(m.prometheus_text(
                apply_readme_help(snap, doc_help)))
        else:
            for addr, s in by_addr.items():
                if s is None:
                    sys.stdout.write(f"# {addr}: unreachable\n")
                    continue
                sys.stdout.write(f"# ==== {addr} ====\n")
                sys.stdout.write(m.prometheus_text(
                    apply_readme_help(s, doc_help)))
        return 0

    if len(args.addresses) == 1:
        out = next(iter(by_addr.values()))
    elif args.merged:
        out = m.merge_snapshots(reached)
    else:
        out = by_addr
    json.dump(m.json_safe(out), sys.stdout, indent=args.indent or None)
    sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
