#!/usr/bin/env python
"""Lint/verify a saved program bundle from the command line.

Usage:
    python tools/lint_program.py <model_dir>          # verify + lint
    python tools/lint_program.py <model_dir> --strict # warnings fail too
    python tools/lint_program.py <model_dir> --json   # machine-readable

``model_dir`` is a ``save_inference_model`` bundle (a directory holding a
``__model__`` file — a ModelRegistry version directory works as-is) OR a
bare ``__model__``-format JSON file. The program is parsed WITHOUT loading
persistables or touching an executor, so the tool runs anywhere the repo
imports (no TPU, no scope state) and is safe on untrusted bundles.

Prints one line per finding::

    PTL003 error block 0 op#4(conv2d): input 'w' is not declared ...

Exit code: 0 clean (or warnings only), 1 on verifier errors (or any
finding under --strict), 2 on unreadable input.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_program_meta(path):
    """Returns (program, feed_names, fetch_names) from a bundle dir or a
    raw __model__ JSON file, without executing anything."""
    model_file = path
    if os.path.isdir(path):
        model_file = os.path.join(path, "__model__")
    with open(model_file) as f:
        meta = json.load(f)
    from paddle_tpu.fluid.framework import Program
    program = Program.from_dict(meta)
    return (program, meta.get("feed_var_names", []),
            meta.get("fetch_var_names", []))


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="static-analyze a saved inference bundle")
    ap.add_argument("model_dir", help="save_inference_model bundle dir, "
                                      "registry version dir, or __model__ "
                                      "JSON file")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on warnings too")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as a JSON array")
    args = ap.parse_args(argv)

    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

    try:
        program, feeds, fetches = load_program_meta(args.model_dir)
    except (OSError, json.JSONDecodeError, KeyError, ValueError) as e:
        print(f"lint_program: cannot read {args.model_dir!r}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2

    from paddle_tpu.fluid.analysis import (ERROR, lint_program,
                                           verify_program)
    diags = verify_program(program, feed_names=feeds, fetch_names=fetches,
                           raise_on_error=False)
    diags += lint_program(program, fetch_names=fetches)

    if args.as_json:
        print(json.dumps([{
            "code": d.code, "severity": d.severity, "message": d.message,
            "block": d.block_idx, "op": d.op_idx, "op_type": d.op_type,
            "var": d.var} for d in diags], indent=2))
    else:
        for d in diags:
            print(d)
        errors = sum(d.severity == ERROR for d in diags)
        print(f"lint_program: {len(diags)} finding(s), {errors} error(s) "
              f"in {args.model_dir}")

    if any(d.severity == ERROR for d in diags):
        return 1
    if args.strict and diags:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
