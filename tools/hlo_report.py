"""Dump XLA cost analysis + per-fusion HBM traffic for the bench step.

Builds the flagship ResNet-50 training step exactly as bench.py runs it,
AOT-compiles it for the attached backend, and reports:
  * total bytes accessed / flops from compiled.cost_analysis()
  * the optimized HLO's largest instructions by operand+result bytes
    (a static estimate: shapes of each fusion's parameters and root)

Usage: python tools/hlo_report.py [--batch 256] [--top 40] [--dump FILE]
"""

import argparse
import collections
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _shape_bytes(shape_str):
    """Bytes of an HLO shape string like 'bf16[256,56,56,64]{...}' or a
    tuple '(bf16[...], f32[...])'."""
    total = 0
    for m in re.finditer(r"(f64|f32|bf16|f16|s64|s32|u64|u32|s8|u8|pred)"
                         r"\[([0-9,]*)\]", shape_str):
        dt, dims = m.groups()
        size = {"f64": 8, "s64": 8, "u64": 8, "f32": 4, "s32": 4, "u32": 4,
                "bf16": 2, "f16": 2, "s8": 1, "u8": 1, "pred": 1}[dt]
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * size
    return total


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--dump", default=None, help="write optimized HLO here")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import bench
    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.executor import _collect_free_inputs, _written_names, _RNG_KEY

    batch, image_size, class_dim = args.batch, 224, 1000
    main_prog, startup, avg_loss = bench.build(batch, image_size, class_dim)

    rng = np.random.RandomState(0)
    img_shape = (batch, image_size, image_size, 3)
    feeds = {
        "img": jnp.zeros(img_shape, jnp.bfloat16),
        "label": jnp.zeros((batch, 1), jnp.int32),
    }

    scope = fluid.Scope()
    exe = fluid.Executor(mode="jit", donate=True, amp=True)
    with jax.default_matmul_precision("bfloat16"):
        exe.run(startup, scope=scope)

        block = main_prog.global_block()
        free = _collect_free_inputs(main_prog, 0)
        state_in = tuple(n for n in free if n not in feeds and scope.has_var(n))
        written = _written_names(main_prog, 0)
        state_out = tuple(n for n in written
                          if (block.has_var(n) and block.var(n).persistable)
                          or scope.has_var(n))
        fn = exe._compiled(main_prog, tuple(sorted(feeds)),
                           (avg_loss.name,), state_in, state_out)
        state = {n: scope.find_var(n) for n in state_in}
        state[_RNG_KEY] = scope.find_var(_RNG_KEY)

        from paddle_tpu.core.amp import amp_guard
        with amp_guard(True):
            lowered = fn.lower(state, feeds)
        compiled = lowered.compile()

    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    print(f"bytes accessed: {ca.get('bytes accessed', 0) / 1e9:.2f} GB")
    print(f"flops:          {ca.get('flops', 0) / 1e12:.2f} TFLOP")
    for k, v in sorted(ca.items()):
        if "bytes accessed" in k and k != "bytes accessed" and v > 1e8:
            print(f"  {k}: {v/1e9:.2f} GB")

    hlo = compiled.as_text()
    if args.dump:
        with open(args.dump, "w") as f:
            f.write(hlo)
        print(f"optimized HLO -> {args.dump} ({len(hlo)/1e6:.1f} MB)")

    # static per-instruction traffic estimate from the entry computation:
    # every non-fused top-level instruction's operand+result bytes
    lines = hlo.splitlines()
    entry = []
    in_entry = False
    for ln in lines:
        if ln.startswith("ENTRY "):
            in_entry = True
            continue
        if in_entry:
            if ln.startswith("}"):
                break
            entry.append(ln.strip())

    rows = []
    kind_totals = collections.Counter()
    for ln in entry:
        m = re.match(r"(%?[\w.\-]+) = (.+?) (\w+)\(", ln)
        if not m:
            continue
        name, shape_str, kind = m.groups()
        if kind in ("parameter", "constant", "get-tuple-element", "tuple",
                    "bitcast"):
            continue
        result_b = _shape_bytes(shape_str)
        # operand shapes: any type[dims] appearing after the opcode's '('
        rest = ln[m.end():]
        operand_b = _shape_bytes(rest)
        total = result_b + operand_b
        rows.append((total, result_b, kind, name, ln[:160]))
        kind_totals[kind] += total

    rows.sort(reverse=True)
    print(f"\ntop-level instructions: {len(rows)}")
    print("\ntraffic by instruction kind (static estimate):")
    for k, v in kind_totals.most_common(12):
        print(f"  {k:24s} {v/1e9:7.2f} GB")
    print(f"\ntop {args.top} instructions by (operands+result) bytes:")
    for total, result_b, kind, name, snippet in rows[:args.top]:
        print(f"  {total/1e9:6.2f} GB  {snippet}")


if __name__ == "__main__":
    sys.exit(main())
