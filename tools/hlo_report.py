"""Dump XLA cost analysis + per-instruction HBM traffic for a target.

Argument parsing over ``obs.perf.attribute``: AOT-compiles the target
for the attached backend and reports ``compiled.cost_analysis()`` totals
(bytes accessed / flops) merged with the optimized HLO's largest
instructions by static operand+result bytes. Default target is the
flagship ResNet-50 training step exactly as bench.py runs it;
``--bundle DIR`` retargets any ``save_inference_model`` export or
registry version dir (tools/profile_common.py is the shared
scaffolding).

Usage: python tools/hlo_report.py [--batch 256] [--top 40] [--dump FILE]
                                  [--bundle DIR]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import profile_common


def main():
    ap = argparse.ArgumentParser()
    profile_common.add_target_args(ap)
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--dump", default=None, help="write optimized HLO here")
    args = ap.parse_args()

    from paddle_tpu.obs import perf

    target = profile_common.build_target(args)
    print(f"target: {target.label}")
    with target.ctx():
        res = perf.attribute(target.program, feed=target.feeds[0],
                             fetch_list=target.fetch_names,
                             executor=target.exe, scope=target.scope,
                             top=args.top, dump_hlo=args.dump)

    cost = res["cost"]
    ba = cost.get("bytes_accessed") or 0
    fl = cost.get("flops") or 0
    print(f"bytes accessed: {ba / 1e9:.2f} GB")
    print(f"flops:          {fl / 1e12:.2f} TFLOP")
    for k, v in sorted(cost.get("detail", {}).items()):
        print(f"  {k}: {v/1e9:.2f} GB")
    if args.dump:
        print(f"optimized HLO -> {args.dump}")

    print(f"\ntop-level instructions: {res['instructions']}")
    print("\ntraffic by instruction kind (static estimate):")
    for k, v in list(res["kind_totals"].items())[:12]:
        print(f"  {k:24s} {v/1e9:7.2f} GB")
    print(f"\ntop {args.top} instructions by (operands+result) bytes:")
    for row in res["rows"]:
        print(f"  {row['bytes']/1e9:6.2f} GB  {row['hlo']}")


if __name__ == "__main__":
    sys.exit(main())
